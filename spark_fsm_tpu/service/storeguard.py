"""Store-outage survival (ISSUE 14) — make a store outage a STALL,
not a failure.

Every durable artifact (journal, leases, checkpoints, result sink,
trace spine, rescache, autoscale records) lives in ONE Redis namespace,
so before this module a store blip was the single fault that degraded
correctness posture fleet-wide: running jobs terminally failed at their
next fenced write, every replica self-fenced as renewals lapsed, and
the control plane went leaderless.  This module is the guard between
the durable-write paths and that fate:

- **Health state machine** (healthy → flaky → down): driven by the
  transport-error streaks the write paths report (``note_error``) plus
  an ACTIVE probe on its own short-timeout connection
  (``store.probe``).  DOWN requires the probe's confirmation — a
  single write failure, or a store that answers the probe but errors
  on writes (sick, not gone), keeps today's conservative posture:
  raise, retry, fence.  When in doubt, fence.

- **Write-behind spool**: while DOWN, a running job's fenced writes
  (checkpoint deltas, result sink, statuses, spine chunks) append to a
  bounded per-job local spool instead of raising.  On store return the
  spool replays IN ORDER under the SAME fencing token: the replay gate
  is one journal-gated NX reacquire (:meth:`~spark_fsm_tpu.service.
  lease.LeaseManager.reacquire_for_spool`) — if the lease was
  legitimately taken during the outage (an adopter owns the uid now),
  the replay is REFUSED and counted, preserving the PR 8
  no-double-commit invariant verbatim (docs/DESIGN.md proves it).
  Spool overflow fences the job — the current terminal-failure path,
  never silent loss, never a partial replay accepted.

- **Outage-aware stalls**: a lease holder whose renewals fail while
  the probe proves the store unreachable PAUSES at its next jobctl
  safe point (``jobctl.stall_entry``) with the frontier kept in memory
  + spool, instead of raising terminal ``LEASE_LOST``; on store return
  it re-acquires through the journal-gated NX path and resumes.  A
  replica that cannot prove a global outage (probe says the store is
  alive) self-fences conservatively, and ``stall_max_s`` bounds how
  long optimism may run.

- **Admission during an outage** sheds 429 by default (the submit
  cannot be journaled, so it cannot be made durable); under
  ``[storeguard] ephemeral_admission`` the Miner instead admits
  loudly-flagged NO-JOURNAL jobs whose writes ride the spool ungated.

Fault sites: ``storeguard.probe`` (an injected raise IS a failed
probe — drives the machine to DOWN deterministically) and
``storeguard.replay`` (wraps every replayed write — injection must
degrade to the terminal-failure path, never corrupt).

Disabled (``[storeguard] enabled = false``, the default): no guard
objects exist, :func:`get` returns None, and every durable-write path
pays exactly one ``is None`` read — scripts/bench_smoke.sh's dispatch
counters stay byte-identical.

Integrity envelopes (ISSUE 18) need no handling here: callers compose
the checksum envelope at value-production time, BEFORE the spool-vs-
direct dispatch, so a spooled write replays the already-enveloped bytes
verbatim and verify-on-read sees one format either way.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_fsm_tpu import config
from spark_fsm_tpu.utils import faults, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event

HEALTHY, FLAKY, DOWN = "healthy", "flaky", "down"
_STATE_NUM = {HEALTHY: 0, FLAKY: 1, DOWN: 2}

_HEALTH = obs.REGISTRY.gauge(
    "fsm_store_health_state",
    "store health as seen by the guard (0 healthy, 1 flaky, 2 down)")
_HEALTH.set(0)
_TRANSITIONS = (obs.REGISTRY.counter(
    "fsm_storeguard_transitions_total",
    "store health state transitions, by destination state")
    .seed(state=HEALTHY).seed(state=FLAKY).seed(state=DOWN))
_PROBES = (obs.REGISTRY.counter(
    "fsm_storeguard_probes_total",
    "active store health probes, by outcome (unreachable = transport "
    "failure; error = the store answered but is sick — fence posture)")
    .seed(outcome="ok").seed(outcome="unreachable").seed(outcome="error"))
_SPOOLED = (obs.REGISTRY.counter(
    "fsm_storeguard_spooled_writes_total",
    "durable writes deferred into the write-behind spool, by verb")
    .seed(verb="set").seed(verb="rpush").seed(verb="delete")
    .seed(verb="incr").seed(verb="spine").seed(verb="status"))
_SPOOL_ENTRIES = obs.REGISTRY.gauge(
    "fsm_storeguard_spool_entries",
    "writes currently held in the write-behind spool (must drain to 0 "
    "after every outage)")
_SPOOL_ENTRIES.set(0)
_REPLAYS = (obs.REGISTRY.counter(
    "fsm_storeguard_replays_total",
    "per-job spool replays after an outage, by outcome (refused = the "
    "lease was legitimately taken during the outage — each one is a "
    "double-commit that did NOT happen)")
    .seed(outcome="ok").seed(outcome="refused").seed(outcome="error"))
_REPLAYED_WRITES = obs.REGISTRY.counter(
    "fsm_storeguard_replayed_writes_total",
    "individual spooled writes applied on store return")
_DROPPED = (obs.REGISTRY.counter(
    "fsm_storeguard_dropped_writes_total",
    "spooled writes dropped without landing, by why (overflow = the "
    "per-job bound; refused = replay gate; error = replay failure)")
    .seed(why="overflow").seed(why="refused").seed(why="error"))
_STALLS = (obs.REGISTRY.counter(
    "fsm_storeguard_stalls_total",
    "outage stalls at jobctl safe points, by outcome")
    .seed(outcome="entered").seed(outcome="resumed").seed(outcome="fenced"))
_OUTAGE_SHEDS = obs.REGISTRY.counter(
    "fsm_storeguard_outage_sheds_total",
    "train submits shed with 429 because the store was down (durable "
    "admission impossible)")
_EPHEMERAL = obs.REGISTRY.counter(
    "fsm_storeguard_ephemeral_admissions_total",
    "loudly-flagged no-journal jobs admitted during a store outage "
    "([storeguard] ephemeral_admission)")


class _JobSpool:
    """One job's ordered write-behind spool.  ``token`` is the fencing
    token held when the spool opened — the replay gate re-proves it;
    ``gate = "none"`` (ephemeral/no-lease jobs) replays unconditionally
    (no other replica can know the uid)."""

    __slots__ = ("uid", "token", "gate", "entries", "overflowed",
                 "started")

    def __init__(self, uid: str, token: Optional[int], gate: str):
        self.uid = uid
        self.token = token
        self.gate = gate
        self.entries: List[Tuple] = []
        self.overflowed = False
        # True once the first entry has been applied: a partially
        # replayed spool ("again" residue) must not re-run its gate
        # checks against its OWN landed prefix
        self.started = False


class StoreGuard:
    """One per process (module-installed, like the obsplane): owns the
    health state machine, the spool, the stall registry and the probe
    thread.  ``clock`` is injectable (tests drive virtual time);
    ``probe_every_s = 0`` means manual ticks."""

    def __init__(self, store, lease_mgr=None, scfg=None,
                 clock=time.monotonic) -> None:
        scfg = scfg if scfg is not None else config.get_config().storeguard
        self.store = store
        self._mgr = lease_mgr
        self.probe_every_s = float(scfg.probe_every_s)
        self.down_after = int(scfg.down_after)
        self.spool_max_entries = int(scfg.spool_max_entries)
        self.stall_max_s = float(scfg.stall_max_s)
        self.ephemeral_admission = bool(scfg.ephemeral_admission)
        self._clock = clock
        self._state = HEALTHY
        self._consecutive = 0
        self._down_since: Optional[float] = None
        self._next_probe = 0.0
        # insertion-ordered: replay walks jobs in first-spooled order,
        # and each job's entries strictly FIFO
        self._spools: Dict[str, _JobSpool] = {}
        # uids whose gate="none" spool ALREADY replayed here: their
        # store trace is our own, so a later outage's spool for the
        # same uid must not read it as foreign (an ephemeral job
        # spanning two outages would otherwise refuse itself)
        self._own_none_uids: set = set()
        # id(ctl) -> (ctl, stalled_since) — strong refs until unstall
        self._stalled: Dict[int, Tuple[object, float]] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        return self._state

    def is_down(self) -> bool:
        return self._state == DOWN

    def _to(self, state: str, why: str = "") -> None:
        if state == self._state:
            return
        self._state = state
        _HEALTH.set(_STATE_NUM[state])
        _TRANSITIONS.inc(state=state)
        self._down_since = self._clock() if state == DOWN else None
        log_event("storeguard_state", state=state, why=why,
                  spooled=self.spool_entries())
        obs.trace_event("storeguard_state", state=state, why=why)

    @staticmethod
    def _is_transport(exc: BaseException) -> bool:
        # OSError covers ConnectionError, socket.timeout, TimeoutError
        # and RespProtocolError; RespError (the store ANSWERED with an
        # error) and injected FaultInjected are deliberately excluded —
        # a store that talks back is sick, not gone: fence posture
        return isinstance(exc, OSError)

    def note_error(self, exc: BaseException) -> bool:
        """Classify one durable-write failure; True when the store is
        (now confirmed) DOWN and the caller should spool instead of
        raising."""
        if not self._is_transport(exc):
            return False
        with self._lock:
            self._consecutive += 1
            streak = self._consecutive
            if self._state == DOWN:
                return True
            if self._state == HEALTHY:
                self._to(FLAKY, why=f"{type(exc).__name__}: {exc}")
            if streak < self.down_after:
                return False
        # streak long enough: consult the probe for the DOWN verdict
        return self.probe_once() == "unreachable"

    def _note_ok(self) -> None:
        if self._consecutive:
            with self._lock:
                self._consecutive = 0
                if self._state == FLAKY and not self._spools:
                    self._to(HEALTHY, why="write succeeded")

    # ------------------------------------------------------------- probe

    def probe_once(self) -> str:
        """One active probe round-trip; drives the state machine.
        Returns "ok" / "unreachable" / "error"."""
        try:
            faults.fault_site("storeguard.probe")
            outcome = "ok" if self.store.probe() else "unreachable"
        except faults.FaultInjected:
            # an injected raise IS a failed probe — the site exists to
            # drive the machine to DOWN deterministically
            outcome = "unreachable"
        except Exception as exc:
            outcome = "unreachable" if self._is_transport(exc) else "error"
        _PROBES.inc(outcome=outcome)
        if outcome == "ok":
            self._on_store_ok()
        elif outcome == "unreachable":
            with self._lock:
                if self._state != DOWN:
                    self._to(DOWN, why="probe unreachable")
        else:
            # the store answered but is sick: NOT an outage — keep the
            # conservative fence posture (flaky at most)
            with self._lock:
                if self._state == DOWN:
                    self._to(FLAKY, why="probe error (store answers)")
        return outcome

    def tick(self) -> None:
        """One maintenance step (the lease heartbeat calls this; the
        probe thread calls it on its own cadence; tests call it
        directly): probe when unhealthy, enforce the stall bound,
        replay any residue, and reap stranded stalls."""
        now = self._clock()
        if self._state != HEALTHY or self._spools:
            if self.probe_every_s <= 0 or now >= self._next_probe:
                self._next_probe = now + max(0.0, self.probe_every_s)
                self.probe_once()
        if self._state == HEALTHY and self._stalled:
            # a stall registered in the race window AFTER a heal's
            # release pass would otherwise park its job forever (the
            # lease keeps renewing, so nothing else ever wakes it) —
            # a healthy guard has no business holding stalls
            self._release_stalls()
        self._enforce_stall_bound(now)

    def start(self) -> None:
        if self.probe_every_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fsm-storeguard")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.probe_every_s):
            try:
                self.tick()
            except Exception as exc:  # the guard thread must never die
                log_event("storeguard_tick_failed", error=str(exc))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(max(2.0, 2 * self.probe_every_s))
            self._thread = None

    # ----------------------------------------------------- durable writes
    # One helper per verb; each: direct while not DOWN (same store fault
    # sites as an unguarded deployment — chaos determinism preserved),
    # spool while DOWN, and a direct transport failure that the probe
    # confirms as an outage converts into a spool append instead of a
    # raise — the write is DEFERRED, the job lives.

    def set(self, uid: str, key: str, value: str,
            gate: Optional[str] = None) -> bool:
        return self._write(uid, ("set", key, value), gate)

    def rpush(self, uid: str, key: str, value: str,
              gate: Optional[str] = None) -> bool:
        return self._write(uid, ("rpush", key, value), gate)

    def delete(self, uid: str, key: str, gate: Optional[str] = None) -> bool:
        return self._write(uid, ("delete", key), gate)

    def incr(self, uid: str, key: str, gate: Optional[str] = None) -> bool:
        return self._write(uid, ("incr", key), gate)

    def status(self, uid: str, status: str,
               gate: Optional[str] = None) -> bool:
        """``add_status`` through the guard: ONE logical spool entry
        for the key-set + log-append pair, so a replay can never tear
        a terminal status from its log entry (the storm checker's
        exactly-once-settlement evidence).  The log timestamp is
        stamped at WRITE time (spool time during an outage), so the
        replayed status log tells the true timeline."""
        ts = int(time.time() * 1000)
        return self._write(uid, ("status", uid, status, ts), gate)

    def spine(self, uid: str, chunk_json: str,
              gate: Optional[str] = None) -> bool:
        return self._write(uid, ("spine", uid, chunk_json), gate)

    def _apply(self, entry: Tuple, replaying: bool = False) -> None:
        verb = entry[0]
        if verb == "set":
            self.store.set(entry[1], entry[2])
        elif verb == "rpush":
            self.store.rpush(entry[1], entry[2])
        elif verb == "delete":
            self.store.delete(entry[1])
        elif verb == "incr":
            self.store.incr(entry[1])
        elif verb == "spine":
            self.store.spine_append(entry[1], entry[2])
        elif verb == "status":
            # the set + log-append pair as one replay unit, idempotent
            # under RE-application (a mid-pair transport failure keeps
            # the whole entry for the next attempt; the tail check
            # keeps an ack-lost append from landing twice).  The tail
            # read is replay-only: the healthy direct path stays the
            # same two verbs add_status always was
            _, uid, status, ts = entry
            payload = f"{ts}:{status}"
            self.store.set(f"fsm:status:{uid}", status)
            log_key = f"fsm:status:log:{uid}"
            if replaying:
                tail = self.store.lrange(log_key)
                if tail and tail[-1] == payload:
                    return
            self.store.rpush(log_key, payload)
        else:  # a spool this process cannot replay would silently lose
            raise ValueError(f"unknown spool verb {verb!r}")

    def _write(self, uid: str, entry: Tuple, gate: Optional[str]) -> bool:
        """Apply (False) or spool (True) one durable write.  A uid with
        a PENDING spool keeps spooling even after the store is back —
        in-order is the invariant, and only the replay may drain it."""
        if self._state != DOWN and uid not in self._spools:
            try:
                self._apply(entry)
                self._note_ok()
                return False
            except Exception as exc:
                if not self.note_error(exc):
                    raise
        self._spool_write(uid, entry, gate)
        return True

    def _ctl_of(self, uid: str):
        if self._mgr is not None:
            ctl = self._mgr.attached_ctl(uid)
            if ctl is not None:
                return ctl
        return jobctl.get(uid)

    def _spool_write(self, uid: str, entry: Tuple,
                     gate: Optional[str]) -> None:
        with self._lock:
            spool = self._spools.get(uid)
            if spool is None:
                if gate is None:
                    token = (self._mgr.token_of(uid)
                             if self._mgr is not None else None)
                    gate = "token" if token is not None else "none"
                else:
                    token = None
                spool = self._spools[uid] = _JobSpool(uid, token, gate)
            if spool.overflowed:
                _DROPPED.inc(why="overflow")
                return
            if len(spool.entries) >= self.spool_max_entries:
                # the bound is the honesty line: past it the job can no
                # longer be deferred losslessly — fence it (terminal at
                # its next safe point) and poison the spool so replay
                # never applies a PARTIAL suffix
                spool.overflowed = True
                dropped = len(spool.entries) + 1
                spool.entries.clear()
                _DROPPED.inc(n=dropped, why="overflow")
                _SPOOL_ENTRIES.set(self.spool_entries())
                jobctl.fence_lost(self._ctl_of(uid))
                log_event("storeguard_spool_overflow", uid=uid,
                          dropped=dropped)
                return
            spool.entries.append(entry)
            _SPOOLED.inc(verb=entry[0])
            _SPOOL_ENTRIES.set(self.spool_entries())

    def spool_entries(self) -> int:
        return sum(len(s.entries) for s in self._spools.values())

    def drained(self) -> bool:
        return not self._spools

    # ------------------------------------------------------------- replay

    def _on_store_ok(self) -> None:
        with self._lock:
            if self._state == HEALTHY and not self._spools:
                return
            if (self._state == FLAKY and self._consecutive
                    and not self._spools and not self._stalled):
                # the probe answers but the WRITE path is failing: the
                # store is sick, not gone — a probe success must not
                # paper over a live failure streak (only a successful
                # write heals flaky, via _note_ok).  With a spool or a
                # stall pending the replay must still be ATTEMPTED —
                # the streak may be a relic of the outage that built
                # them (a DOWN -> flaky -> ok path sees no direct
                # writes to reset it: spooled uids keep spooling and
                # stalled jobs write nothing), and a failed replay
                # re-enters down/flaky on its own evidence anyway
                return
            ok = self._replay_all() if self._spools else True
            if ok:
                self._consecutive = 0
                self._to(HEALTHY, why="store back, spool drained")
                self._release_stalls()
            # not ok: a replay write hit transport again — the state
            # flipped back to DOWN inside _replay_all and the residue
            # (applied prefix popped) waits for the next probe

    def _replay_all(self) -> bool:
        """Replay every job spool in first-spooled order; True when the
        spool set fully drained (each job either applied or dropped
        with its job fenced)."""
        for uid in list(self._spools):
            spool = self._spools.get(uid)
            if spool is None:
                continue
            outcome = self._replay_spool(spool)
            if outcome == "again":
                return False  # store went away mid-replay: keep residue
            self._spools.pop(uid, None)
            _REPLAYS.inc(outcome=outcome)
            if outcome != "ok":
                # a dropped spool may hold THIS replica's deferred
                # admission-marker DEL (the dequeue-during-outage
                # path).  Markers have no TTL and are namespaced per
                # replica, so sweeping our own is always safe — and
                # skipping it would leak a phantom marker a later
                # steal scan could claim for an already-settled uid
                for entry in spool.entries:
                    if (entry[0] == "delete"
                            and entry[1].startswith("fsm:admission:")):
                        try:
                            self.store.delete(entry[1])
                        except Exception:
                            pass  # best effort; recovery adoption also
                            # reaps dead markers
                log_event("storeguard_replay_" + outcome, uid=uid)
        _SPOOL_ENTRIES.set(self.spool_entries())
        return True

    def _replay_spool(self, spool: _JobSpool) -> str:
        if spool.overflowed:
            # fenced at overflow time; nothing left to apply
            return "refused"
        if (spool.gate == "none" and self._mgr is not None
                and not spool.started
                and spool.uid not in self._own_none_uids):
            # ephemeral/no-lease spools replay ungated ONLY while the
            # uid is provably unknown to the durable world: a client
            # that reused the uid against a healthy peer during our
            # outage owns the uid's keys there (journal, lease, or a
            # status some OTHER writer landed), and clobbering them
            # would be the double-commit the token gate exists to
            # prevent.  When in doubt, refuse.
            try:
                foreign = (
                    self.store.peek(f"fsm:journal:{spool.uid}") is not None
                    or self.store.peek(f"fsm:lease:{spool.uid}") is not None
                    or self.store.peek(f"fsm:status:{spool.uid}")
                    is not None)
            except Exception as exc:
                if self._is_transport(exc):
                    self._to(DOWN, why="ephemeral gate transport failure")
                    return "again"
                foreign = True
            if foreign:
                _DROPPED.inc(n=len(spool.entries), why="refused")
                jobctl.fence_lost(self._ctl_of(spool.uid))
                return "refused"
        if spool.gate == "token" and self._mgr is not None:
            try:
                owned = self._mgr.reacquire_for_spool(spool.uid,
                                                      spool.token)
            except Exception as exc:
                if self._is_transport(exc):
                    self._to(DOWN, why="reacquire transport failure")
                    return "again"
                owned = False
            if not owned:
                # the lease was legitimately taken during the outage:
                # an adopter owns the uid's keys — refusing the replay
                # IS the no-double-commit invariant (each refusal a
                # double-commit that did not happen)
                _DROPPED.inc(n=len(spool.entries), why="refused")
                jobctl.fence_lost(self._ctl_of(spool.uid))
                return "refused"
        while spool.entries:
            entry = spool.entries[0]
            try:
                faults.fault_site("storeguard.replay", uid=spool.uid,
                                  verb=entry[0])
                self._apply(entry, replaying=True)
            except Exception as exc:
                if self._is_transport(exc) and self.note_error(exc):
                    # store flapped mid-replay: the applied prefix is
                    # already popped, the residue replays next time —
                    # meta-last write ordering inside the spool keeps
                    # any prefix heal-able (StoreCheckpoint.load)
                    return "again"
                # non-transport (injected storeguard.replay, sick
                # store): degrade to the terminal-failure path — fence
                # the job, drop the rest of ITS spool; the store holds
                # a heal-able prefix, the journal intent (if any) still
                # stands for recovery.  Other jobs' spools still replay.
                _DROPPED.inc(n=len(spool.entries), why="error")
                jobctl.fence_lost(self._ctl_of(spool.uid))
                log_event("storeguard_replay_failed", uid=spool.uid,
                          verb=entry[0], error=str(exc))
                return "error"
            spool.entries.pop(0)
            spool.started = True
            _REPLAYED_WRITES.inc()
            _SPOOL_ENTRIES.set(self.spool_entries())
        if (spool.gate == "token" and self._mgr is not None
                and self._mgr.token_of(spool.uid) is None):
            # the job settled locally during the outage (its release
            # already ran and was a no-op store-side): the replay-time
            # reacquire left a store lease under our token — clean it
            self._mgr.release_token(spool.uid, spool.token)
        if spool.gate == "none":
            # this uid's store trace is now OUR OWN: a later outage's
            # spool for it skips the foreign-uid check (bounded — the
            # set only ever holds this process's ephemeral uids)
            if len(self._own_none_uids) > 4096:
                self._own_none_uids.clear()
            self._own_none_uids.add(spool.uid)
        return "ok"

    # -------------------------------------------------------------- stalls

    def stall_job(self, ctl, uid: str) -> bool:
        """The lease layer's outage hook: called when a holder's
        renewal verification failed past its TTL.  True = the job is
        (now) stalled instead of fenced — only when the probe proves a
        transport-level outage and the stall budget is not exhausted;
        False = keep today's conservative fence."""
        if ctl is None:
            return False
        if self._state != DOWN and self.probe_once() != "unreachable":
            return False  # store alive (or sick): when in doubt, fence
        now = self._clock()
        if (self.stall_max_s and self._down_since is not None
                and now - self._down_since > self.stall_max_s):
            return False
        with self._lock:
            # registry entry and jobctl flag flip ATOMICALLY under the
            # guard lock: a release pass serializes against this, so a
            # stall can never be registered flag-less (or flagged
            # registry-less) in the window around a heal — either the
            # release sees it whole, or the next tick's reap does
            if id(ctl) not in self._stalled:
                self._stalled[id(ctl)] = (ctl, now)
                jobctl.stall_entry(ctl)
                _STALLS.inc(outcome="entered")
                log_event("storeguard_stall", uid=uid)
                obs.trace_event("storeguard_stall", uid=uid)
            else:
                jobctl.stall_entry(ctl)
        return True

    def _enforce_stall_bound(self, now: float) -> None:
        if not self.stall_max_s:
            return
        with self._lock:
            # any unhealthy state counts against the bound: a stall
            # that survives a DOWN -> flaky drift (store answering but
            # sick) must still fence at its deadline, or the config
            # contract ("longest a job may stall before it fences
            # conservatively") silently becomes "forever"
            expired = [(k, ctl) for k, (ctl, since) in self._stalled.items()
                       if now - since > self.stall_max_s
                       and self._state != HEALTHY]
            for k, ctl in expired:
                self._stalled.pop(k, None)
                # optimism budget spent: fence conservatively — the
                # journal intent survives for recovery, nothing is lost
                jobctl.fence_lost(ctl)
                jobctl.unstall_entry(ctl)
                _STALLS.inc(outcome="fenced")
                log_event("storeguard_stall_fenced",
                          uid=getattr(ctl, "uid", "?"))

    def _release_stalls(self) -> None:
        with self._lock:
            stalled = list(self._stalled.values())
            self._stalled.clear()
            for ctl, _ in stalled:
                outcome = ("fenced" if getattr(ctl, "lease_lost", False)
                           else "resumed")
                jobctl.unstall_entry(ctl)
                _STALLS.inc(outcome=outcome)
                log_event("storeguard_stall_" + outcome,
                          uid=getattr(ctl, "uid", "?"))

    # ------------------------------------------------------------- surface

    def shed_outage_admission(self) -> int:
        """Count one outage shed; returns the Retry-After hint (the
        probe cadence is how fast the service can notice the store
        back — two probe periods is the honest earliest)."""
        _OUTAGE_SHEDS.inc()
        return max(1, int(2 * max(self.probe_every_s, 0.5)) + 1)

    def note_ephemeral_admission(self) -> None:
        _EPHEMERAL.inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_errors": self._consecutive,
                "down_since_s": (None if self._down_since is None
                                 else round(self._clock()
                                            - self._down_since, 3)),
                "spool_jobs": len(self._spools),
                "spool_entries": self.spool_entries(),
                "stalled_jobs": len(self._stalled),
                "probe_every_s": self.probe_every_s,
                "down_after": self.down_after,
                "spool_max_entries": self.spool_max_entries,
                "stall_max_s": self.stall_max_s,
                "ephemeral_admission": self.ephemeral_admission,
            }


# ---------------------------------------------------------------------------
# Process-global installation (the same last-wins posture as the
# obsplane: tests build many Miners; the service builds one)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_guard: Optional[StoreGuard] = None


def install(store, lease_mgr=None, scfg=None, clock=time.monotonic
            ) -> StoreGuard:
    global _guard
    guard = StoreGuard(store, lease_mgr=lease_mgr, scfg=scfg, clock=clock)
    with _lock:
        _guard = guard
    if lease_mgr is not None:
        lease_mgr.attach_guard(guard)
    return guard


def uninstall() -> None:
    """Remove the guard (test isolation); resets the health gauge."""
    global _guard
    with _lock:
        g, _guard = _guard, None
    if g is not None:
        g.stop()
    _HEALTH.set(0)


def get() -> Optional[StoreGuard]:
    """The installed guard, or None — the one read every durable-write
    path pays on a [storeguard]-disabled deployment."""
    return _guard
