"""Resource attribution & usage metering plane (ISSUE 19).

Every unit of device work the platform dispatches — broker launches,
direct engine evals, resident-frontier segments, SPAM waves, predict
scoring waves — is attributed to the JOB that caused it, and through
the job's ``JobControl.tenant`` to the tenant, under a *conservation
invariant*: summed per-job attribution equals the existing global
dispatch counters exactly.

Integer quantities (launches, traffic units) are split across the jobs
sharing a launch by **lane share** with largest-remainder apportionment
(:func:`split_integral`) — the per-lane ``Launch.jobs`` tags the fusion
broker already plans with are the ground truth of who occupied the
device, and integer apportionment sums back to the launch total
EXACTLY, which re-running the cost model per job would not (per-job
re-plans see different pad/overhead and their sum drifts from what was
actually dispatched).  Float quantities (estimated and measured device
seconds) split proportionally to traffic share.

Attribution lands in three places:

* live per-job accumulators (``deposit``), mirrored onto the owning
  ``JobControl.usage`` and carried across kill -9/adoption inside the
  ``frontier_state`` checkpoint (``checkpoint_snapshot`` / ``resume``
  — resume REPLACES, never adds, so an adopter re-depositing its own
  work can never double-bill);
* per-tenant windowed rollups (``settle``), credited with the *avoided*
  cost of rescache exact/dominated/coalesced serves priced from the
  cached entry's recorded usage (``credit_avoided``);
* a durable per-tenant ledger — enveloped ``fsm:usage:{tenant}``
  records flushed on the lease heartbeat (cluster) or a private timer
  (solo).  Job entries inside a ledger record are keyed by uid and
  REPLACED on re-flush, so an adopter's final settle overwrites the
  dead replica's partial entry instead of double-billing; a job whose
  lease is lost at flush time is fenced out of the flush entirely (the
  adopter owns its ledger row now).

Disabled posture (``[usage] enabled = false``, the default off state):
every probe returns after ONE module-global read (``_meter is None``)
— the same contract as ``fusion.dispatch_wave`` and ``faults._active``,
pinned by test_usage.py and bench_smoke's byte-identical counters.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from spark_fsm_tpu.utils import envelope, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event

#: durable key prefix for the per-tenant ledger records
LEDGER_PREFIX = "fsm:usage:"

#: the per-job attribution vector — every surface deposits these five
FIELDS = ("device_seconds_est", "device_seconds_measured", "launches",
          "traffic_units", "readback_bytes")

#: ledger records keep at most this many per-uid job entries per tenant;
#: older entries age out with their contribution FROZEN into the
#: record's totals (they can no longer be replaced by an adopter —
#: adoption happens within seconds, eviction after dozens of jobs)
LEDGER_JOBS_CAP = 64

# -- zero-seeded metric families (always registered, even disabled) -------
_DEVICE_SECONDS = obs.REGISTRY.counter(
    "fsm_usage_device_seconds_total",
    "measured device-seconds attributed to jobs, by tenant").seed(
        tenant="default")
_LAUNCHES = obs.REGISTRY.counter(
    "fsm_usage_launches_total",
    "device launches attributed to jobs, by tenant — sums exactly to "
    "the global dispatch counters (conservation invariant)").seed(
        tenant="default")
_TRAFFIC = obs.REGISTRY.counter(
    "fsm_usage_traffic_units_total",
    "cost-model traffic units attributed to jobs, by tenant").seed(
        tenant="default")
_AVOIDED = obs.REGISTRY.counter(
    "fsm_usage_avoided_device_seconds_total",
    "device-seconds NOT spent thanks to rescache serves, priced from "
    "the cached entry's recorded usage, by tenant").seed(
        tenant="default")
_FLUSHES = obs.REGISTRY.counter(
    "fsm_usage_flushes_total",
    "durable ledger flushes, by tenant").seed(tenant="default")


def seed_tenant(tenant: str) -> None:
    """Zero-seed every fsm_usage_* family for ``tenant`` (called from
    obsplane.seed_tenant so the fairness vocabulary and the usage
    vocabulary can never drift apart)."""
    for c in (_DEVICE_SECONDS, _LAUNCHES, _TRAFFIC, _AVOIDED, _FLUSHES):
        c.seed(tenant=tenant)


def split_integral(total: int, weights: Sequence[float]) -> List[int]:
    """Deterministic largest-remainder apportionment of an integer
    ``total`` across ``weights``: the result sums to ``total`` EXACTLY.

    Quotas are ``total * w/sum(w)``; every share gets its floor, and
    the leftover units go to the largest fractional remainders
    (ties broken by lowest index, so callers passing weights in sorted
    job order get a stable plurality winner).  Degenerate weights
    (empty sum) fall back to equal shares."""
    n = len(weights)
    if n == 0:
        return []
    total = int(total)
    wsum = float(sum(weights))
    if wsum <= 0:
        weights = [1.0] * n
        wsum = float(n)
    quotas = [total * (float(w) / wsum) for w in weights]
    out = [int(q) for q in quotas]
    rem = total - sum(out)
    if rem > 0:
        order = sorted(range(n), key=lambda i: (out[i] - quotas[i], i))
        for i in order[:rem]:
            out[i] += 1
    return out


def _zero_vector() -> Dict[str, float]:
    return {"device_seconds_est": 0.0, "device_seconds_measured": 0.0,
            "launches": 0, "traffic_units": 0, "readback_bytes": 0}


def _tenant_zero() -> dict:
    z = _zero_vector()
    z.update(avoided_device_seconds=0.0, jobs_settled=0)
    return z


def _add(dst: dict, src: dict, sign: int = 1) -> None:
    for f in FIELDS:
        v = src.get(f) or 0
        dst[f] = dst.get(f, 0) + sign * (float(v) if "seconds" in f
                                         else int(v))


class _JobUsage:
    """Live per-job accumulator (one per in-flight uid)."""

    __slots__ = ("tenant", "device_seconds_est", "device_seconds_measured",
                 "launches", "traffic_units", "readback_bytes")

    def __init__(self, tenant: str = "default"):
        self.tenant = tenant
        self.device_seconds_est = 0.0
        self.device_seconds_measured = 0.0
        self.launches = 0
        self.traffic_units = 0
        self.readback_bytes = 0

    def as_dict(self) -> dict:
        return {"tenant": self.tenant,
                "device_seconds_est": round(self.device_seconds_est, 9),
                "device_seconds_measured": round(
                    self.device_seconds_measured, 9),
                "launches": self.launches,
                "traffic_units": self.traffic_units,
                "readback_bytes": self.readback_bytes}

    @classmethod
    def from_dict(cls, d: dict) -> "_JobUsage":
        j = cls(str(d.get("tenant") or "default"))
        j.device_seconds_est = float(d.get("device_seconds_est") or 0.0)
        j.device_seconds_measured = float(
            d.get("device_seconds_measured") or 0.0)
        j.launches = int(d.get("launches") or 0)
        j.traffic_units = int(d.get("traffic_units") or 0)
        j.readback_bytes = int(d.get("readback_bytes") or 0)
        return j


class Meter:
    """The process-wide usage meter: live job accumulators, per-tenant
    rollups + sliding window, avoided-cost credits, and the durable
    ledger flusher."""

    def __init__(self, *, window_s: float = 300.0,
                 flush_every_s: float = 15.0, top_jobs: int = 10,
                 max_recent: int = 512):
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobUsage] = {}
        self._tenants: Dict[str, dict] = {"default": _tenant_zero()}
        # settled-but-unflushed job vectors, keyed by uid (the durable
        # flush unit); replaced wholesale if the same uid settles again
        self._pending: Dict[str, dict] = {}
        self._recent: "OrderedDict[str, dict]" = OrderedDict()
        self._avoided_delta: Dict[str, float] = {}
        # read-path (jobless) deposits awaiting durable flush — the
        # predict plane's waves have no JobControl/lease, so their cost
        # folds straight into the tenant, keyed for append-only merge
        self._read_delta: Dict[str, dict] = {}
        self._window = obs.SlidingQuantiles(window_s=window_s)
        self.flush_every_s = float(flush_every_s)
        self.top_jobs = int(top_jobs)
        self.max_recent = int(max_recent)
        self.store = None
        self.mgr = None
        self._last_flush = 0.0
        self.flushes = 0
        self.flush_errors = 0
        self.fenced = 0
        self.ledger_corrupt = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------- attribution

    def _tenant_of(self, uid: str) -> str:
        ctl = jobctl.get(uid)
        return getattr(ctl, "tenant", None) or "default"

    def deposit(self, uid: str, *, launches: int = 0,
                traffic_units: int = 0, seconds_est: float = 0.0,
                seconds_measured: float = 0.0,
                readback_bytes: int = 0) -> None:
        ctl = jobctl.get(uid)
        tenant = (getattr(ctl, "tenant", None) or "default")
        with self._lock:
            j = self._jobs.get(uid)
            if j is None:
                j = self._jobs[uid] = _JobUsage(tenant)
                if ctl is not None:
                    ctl.usage = j
            j.tenant = tenant
            j.launches += int(launches)
            j.traffic_units += int(traffic_units)
            j.device_seconds_est += float(seconds_est)
            j.device_seconds_measured += float(seconds_measured)
            j.readback_bytes += int(readback_bytes)
        if launches:
            _LAUNCHES.inc(int(launches), tenant=tenant)
        if traffic_units:
            _TRAFFIC.inc(int(traffic_units), tenant=tenant)
        if seconds_measured:
            _DEVICE_SECONDS.inc(float(seconds_measured), tenant=tenant)

    def deposit_tenant(self, tenant_raw: Optional[str], *,
                       launches: int = 0, traffic_units: int = 0,
                       seconds_est: float = 0.0,
                       seconds_measured: float = 0.0,
                       readback_bytes: int = 0) -> None:
        """Attribute JOBLESS device work (the predict read path)
        straight to a tenant: no JobControl, no lease, no per-job
        ledger entry — the cost folds into the tenant rollup live and
        rides the next durable flush as an append-only delta."""
        from spark_fsm_tpu.service import obsplane

        tenant = (tenant_raw if tenant_raw in obsplane.known_tenants()
                  else obsplane.DEFAULT_TENANT)
        vec = {"device_seconds_est": float(seconds_est),
               "device_seconds_measured": float(seconds_measured),
               "launches": int(launches),
               "traffic_units": int(traffic_units),
               "readback_bytes": int(readback_bytes)}
        with self._lock:
            roll = self._tenants.setdefault(tenant, _tenant_zero())
            _add(roll, vec)
            delta = self._read_delta.setdefault(tenant, _zero_vector())
            _add(delta, vec)
        if launches:
            _LAUNCHES.inc(int(launches), tenant=tenant)
        if traffic_units:
            _TRAFFIC.inc(int(traffic_units), tenant=tenant)
        if seconds_measured:
            _DEVICE_SECONDS.inc(float(seconds_measured), tenant=tenant)

    def settle(self, uid: str) -> Optional[dict]:
        """Fold ``uid``'s accumulator into its tenant rollup and queue
        it for the durable ledger; returns the job's usage vector (the
        ``stats["usage"]`` block) or None when nothing was deposited."""
        with self._lock:
            j = self._jobs.pop(uid, None)
            if j is None:
                return None
            vec = j.as_dict()
            roll = self._tenants.setdefault(j.tenant, _tenant_zero())
            _add(roll, vec)
            roll["jobs_settled"] += 1
            self._pending[uid] = dict(vec, ts=round(time.time(), 3))
            self._recent[uid] = vec
            while len(self._recent) > self.max_recent:
                self._recent.popitem(last=False)
        self._window.observe(
            vec["device_seconds_measured"] or vec["device_seconds_est"],
            tenant=j.tenant)
        return vec

    def job_view(self, uid: str) -> Optional[dict]:
        with self._lock:
            j = self._jobs.get(uid)
            return j.as_dict() if j is not None else None

    def checkpoint_snapshot(self, uid: str) -> Optional[dict]:
        return self.job_view(uid)

    def resume(self, uid: str, snap: dict) -> None:
        """Adopt a checkpointed accumulator: REPLACE, never add — the
        dead holder's deposits are inside ``snap``, and the adopter's
        own re-deposits land on top of it.  Prometheus counters are NOT
        replayed (they count THIS process's dispatches only, which is
        what the conservation invariant compares them against)."""
        if not isinstance(snap, dict):
            return
        j = _JobUsage.from_dict(snap)
        with self._lock:
            self._jobs[uid] = j
        ctl = jobctl.get(uid)
        if ctl is not None:
            ctl.usage = j

    def drop(self, uid: str) -> None:
        """Forget a live accumulator without settling (fenced holder:
        the adopter owns the job's attribution now)."""
        with self._lock:
            self._jobs.pop(uid, None)

    def credit_avoided(self, tenant_raw: Optional[str], seconds: float,
                       mode: str) -> None:
        from spark_fsm_tpu.service import obsplane

        seconds = max(0.0, float(seconds or 0.0))
        tenant = (tenant_raw if tenant_raw in obsplane.known_tenants()
                  else obsplane.DEFAULT_TENANT)
        with self._lock:
            roll = self._tenants.setdefault(tenant, _tenant_zero())
            roll["avoided_device_seconds"] += seconds
            self._avoided_delta[tenant] = (
                self._avoided_delta.get(tenant, 0.0) + seconds)
        _AVOIDED.inc(seconds, tenant=tenant)
        log_event("usage_avoided_credit", tenant=tenant, mode=mode,
                  device_seconds=round(seconds, 6))

    # ---------------------------------------------------- durable ledger

    def tick(self) -> None:
        """Heartbeat-cadence flush hook (lease.LeaseManager.tick in
        cluster mode, the private timer thread solo)."""
        now = time.monotonic()
        if now - self._last_flush < self.flush_every_s:
            return
        with self._lock:
            dirty = (bool(self._pending) or bool(self._avoided_delta)
                     or bool(self._read_delta))
        if dirty:
            self.flush_now()
        else:
            self._last_flush = now

    def flush_now(self) -> int:
        """Merge every pending settled job into its tenant's durable
        ledger record.  Per-uid fencing: a pending job whose lease this
        replica has lost is dropped, not written — the adopter owns its
        ledger row.  Returns the number of tenants flushed."""
        store = self.store
        if store is None:
            return 0
        with self._lock:
            pending = self._pending
            self._pending = {}
            avoided = self._avoided_delta
            self._avoided_delta = {}
            read_delta = self._read_delta
            self._read_delta = {}
        self._last_flush = time.monotonic()
        mgr = self.mgr
        by_tenant: Dict[str, Dict[str, dict]] = {}
        for uid, vec in pending.items():
            if mgr is not None:
                try:
                    if mgr.is_lost(uid):
                        self.fenced += 1
                        log_event("usage_flush_fenced", uid=uid)
                        continue
                except Exception:
                    pass
            by_tenant.setdefault(
                str(vec.get("tenant") or "default"), {})[uid] = vec
        for t in list(avoided) + list(read_delta):
            by_tenant.setdefault(t, {})
        flushed = 0
        for tenant, jobs in by_tenant.items():
            try:
                self._flush_tenant(store, tenant, jobs,
                                   avoided.get(tenant, 0.0),
                                   read_delta.get(tenant))
                flushed += 1
            except Exception as exc:
                self.flush_errors += 1
                log_event("usage_flush_error", tenant=tenant,
                          error=str(exc))
                # put the jobs back so the next flush retries them (an
                # adopter's later settle for the same uid still wins —
                # pending is keyed by uid and setdefault keeps newest)
                with self._lock:
                    for uid, vec in jobs.items():
                        self._pending.setdefault(uid, vec)
                    if avoided.get(tenant):
                        self._avoided_delta[tenant] = (
                            self._avoided_delta.get(tenant, 0.0)
                            + avoided[tenant])
                    if read_delta.get(tenant):
                        rd = self._read_delta.setdefault(
                            tenant, _zero_vector())
                        _add(rd, read_delta[tenant])
        return flushed

    def _flush_tenant(self, store, tenant: str, jobs: Dict[str, dict],
                      avoided_delta: float,
                      read_delta: Optional[dict] = None) -> None:
        key = LEDGER_PREFIX + tenant
        rec = None
        payload, verdict = envelope.unwrap(store.peek(key))
        if verdict == "corrupt":
            self.ledger_corrupt += 1
            log_event("usage_ledger_corrupt", tenant=tenant)
        elif payload is not None:
            try:
                rec = json.loads(payload)
                if not isinstance(rec, dict):
                    rec = None
            except ValueError:
                self.ledger_corrupt += 1
                rec = None
        if rec is None:
            rec = {"tenant": tenant, "totals": _zero_vector(),
                   "avoided_device_seconds": 0.0, "jobs": {},
                   "jobs_settled": 0}
        totals = rec.setdefault("totals", _zero_vector())
        led_jobs = rec.setdefault("jobs", {})
        for uid, vec in jobs.items():
            old = led_jobs.get(uid)
            if old is not None:
                # adoption re-settle: REPLACE the dead holder's row —
                # subtract it from totals first, so nothing is billed
                # twice
                _add(totals, old, sign=-1)
            else:
                rec["jobs_settled"] = int(rec.get("jobs_settled") or 0) + 1
            _add(totals, vec)
            led_jobs[uid] = vec
        # age out beyond the cap, oldest settle first; their share is
        # already frozen into totals
        if len(led_jobs) > LEDGER_JOBS_CAP:
            for uid in sorted(led_jobs,
                              key=lambda u: led_jobs[u].get("ts") or 0.0)[
                    :len(led_jobs) - LEDGER_JOBS_CAP]:
                del led_jobs[uid]
        if read_delta is not None:
            # jobless read-path work: append-only merge into totals
            # plus its own sub-vector for visibility
            _add(totals, read_delta)
            rp = rec.setdefault("read_path", _zero_vector())
            _add(rp, read_delta)
        rec["avoided_device_seconds"] = (
            float(rec.get("avoided_device_seconds") or 0.0)
            + float(avoided_delta))
        rec["replica"] = getattr(self.mgr, "replica_id", None)
        rec["ts"] = round(time.time(), 3)
        store.set(key, envelope.wrap(json.dumps(rec)))
        self.flushes += 1
        _FLUSHES.inc(tenant=tenant)

    # --------------------------------------------------- solo flush loop

    def start_solo(self) -> None:
        """Private flush timer for solo boots (no lease heartbeat to
        ride)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="usage-flush", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(min(self.flush_every_s, 2.0)):
            try:
                self.tick()
            except Exception as exc:
                log_event("usage_flush_error", tenant="*",
                          error=str(exc))

    # ------------------------------------------------------------ admin

    def ledger_rows(self, store=None) -> Dict[str, dict]:
        """The merged durable view: one row per ``fsm:usage:{tenant}``
        record (corrupt records skipped + counted)."""
        store = store if store is not None else self.store
        rows: Dict[str, dict] = {}
        if store is None:
            return rows
        for key in store.scan_iter(LEDGER_PREFIX):
            tenant = key[len(LEDGER_PREFIX):]
            payload, verdict = envelope.unwrap(store.peek(key))
            if verdict == "corrupt" or payload is None:
                if verdict == "corrupt":
                    self.ledger_corrupt += 1
                continue
            try:
                rec = json.loads(payload)
            except ValueError:
                self.ledger_corrupt += 1
                continue
            if isinstance(rec, dict):
                rows[tenant] = rec
        return rows

    def report(self, store=None) -> dict:
        """The ``/admin/usage`` body: durable per-tenant table (flushed
        first, so the response is read-your-writes), live in-flight
        jobs, windowed rollups, and the top-N settled jobs by measured
        device seconds."""
        try:
            self.flush_now()
        except Exception:
            pass
        with self._lock:
            tenants = {t: dict(r) for t, r in self._tenants.items()}
            live = {u: j.as_dict() for u, j in self._jobs.items()}
            recent = list(self._recent.items())
        ledger = self.ledger_rows(store)
        for t in tenants:
            tenants[t]["window"] = self._window.stats(tenant=t)
            led = ledger.get(t)
            if led is not None:
                tenants[t]["ledger"] = {
                    "totals": led.get("totals"),
                    "avoided_device_seconds": led.get(
                        "avoided_device_seconds"),
                    "jobs_settled": led.get("jobs_settled"),
                    "ts": led.get("ts"), "replica": led.get("replica")}
        for t, led in ledger.items():
            if t not in tenants:
                # settled by another replica: durable-only row
                row = _tenant_zero()
                row["window"] = self._window.stats(tenant=t)
                row["ledger"] = {
                    "totals": led.get("totals"),
                    "avoided_device_seconds": led.get(
                        "avoided_device_seconds"),
                    "jobs_settled": led.get("jobs_settled"),
                    "ts": led.get("ts"), "replica": led.get("replica")}
                tenants[t] = row
        top = sorted(recent, key=lambda kv: -(
            kv[1].get("device_seconds_measured")
            or kv[1].get("device_seconds_est") or 0.0))[:self.top_jobs]
        totals = _tenant_zero()
        for r in tenants.values():
            _add(totals, r)
            totals["avoided_device_seconds"] += float(
                r.get("avoided_device_seconds") or 0.0)
            totals["jobs_settled"] += int(r.get("jobs_settled") or 0)
        return {"enabled": True, "tenants": tenants, "totals": totals,
                "top_jobs": [dict(v, uid=u) for u, v in top],
                "live_jobs": live, "stats": self.stats()}

    def stats(self) -> dict:
        with self._lock:
            n_live = len(self._jobs)
            n_pending = len(self._pending)
            tenants = len(self._tenants)
        return {"live_jobs": n_live, "pending_flush": n_pending,
                "tenants": tenants, "flushes": self.flushes,
                "flush_errors": self.flush_errors, "fenced": self.fenced,
                "ledger_corrupt": self.ledger_corrupt,
                "flush_every_s": self.flush_every_s}


# -- module wiring (the integrity/obsplane install pattern) ---------------

_cfg = None  # UsageConfig from the boot config; None = defaults (off)
_meter: Optional[Meter] = None


def configure(ucfg) -> None:
    """Adopt the ``[usage]`` boot config (config.set_config).  The
    meter itself is built at :func:`install` — configure only decides
    whether one will exist and with what knobs."""
    global _cfg
    _cfg = ucfg
    m = _meter
    if m is not None and ucfg is not None:
        m.flush_every_s = float(ucfg.flush_every_s)
        m.top_jobs = int(ucfg.top_jobs)
        m._window.set_window(float(ucfg.window_s))


def install(store, lease_mgr=None) -> Optional[Meter]:
    """Install the process-wide meter over ``store`` (Miner init; last
    install wins, mirroring obsplane).  Returns None when the usage
    plane is disabled — every deposit probe then costs one module-
    global read."""
    global _meter
    if _meter is not None:
        _meter.stop()
    if _cfg is None or not _cfg.enabled:
        _meter = None
        return None
    m = Meter(window_s=float(_cfg.window_s),
              flush_every_s=float(_cfg.flush_every_s),
              top_jobs=int(_cfg.top_jobs))
    m.store = store
    m.mgr = lease_mgr
    if lease_mgr is None:
        m.start_solo()
    _meter = m
    return m


def uninstall() -> None:
    global _meter
    if _meter is not None:
        _meter.stop()
    _meter = None


def get() -> Optional[Meter]:
    return _meter


def enabled() -> bool:
    return _meter is not None


# -- one-global-read probes (the fusion.dispatch_wave contract) -----------

def deposit(uid: str, *, launches: int = 0, traffic_units: int = 0,
            seconds_est: float = 0.0, seconds_measured: float = 0.0,
            readback_bytes: int = 0) -> None:
    m = _meter
    if m is None:
        return
    m.deposit(uid, launches=launches, traffic_units=traffic_units,
              seconds_est=seconds_est, seconds_measured=seconds_measured,
              readback_bytes=readback_bytes)


def deposit_tenant(tenant_raw: Optional[str], *, launches: int = 0,
                   traffic_units: int = 0, seconds_est: float = 0.0,
                   seconds_measured: float = 0.0,
                   readback_bytes: int = 0) -> None:
    m = _meter
    if m is None:
        return
    m.deposit_tenant(tenant_raw, launches=launches,
                     traffic_units=traffic_units, seconds_est=seconds_est,
                     seconds_measured=seconds_measured,
                     readback_bytes=readback_bytes)


def settle(uid: str) -> Optional[dict]:
    m = _meter
    if m is None:
        return None
    return m.settle(uid)


def job_view(uid: str) -> Optional[dict]:
    m = _meter
    if m is None:
        return None
    return m.job_view(uid)


def checkpoint_snapshot(uid: str) -> Optional[dict]:
    m = _meter
    if m is None:
        return None
    return m.checkpoint_snapshot(uid)


def resume(uid: str, snap: dict) -> None:
    m = _meter
    if m is None:
        return
    m.resume(uid, snap)


def drop(uid: str) -> None:
    m = _meter
    if m is None:
        return
    m.drop(uid)


def credit_avoided(tenant_raw: Optional[str], seconds: float,
                   mode: str) -> None:
    m = _meter
    if m is None:
        return
    m.credit_avoided(tenant_raw, seconds, mode)


def tick() -> None:
    """Heartbeat-cadence hook (lease.LeaseManager.tick): one global
    read when nothing is installed."""
    m = _meter
    if m is not None:
        m.tick()


def flush_now() -> int:
    m = _meter
    if m is None:
        return 0
    return m.flush_now()


def report(store=None) -> dict:
    m = _meter
    if m is None:
        return {"enabled": False}
    return m.report(store)


def stats() -> Optional[dict]:
    m = _meter
    if m is None:
        return None
    return m.stats()
