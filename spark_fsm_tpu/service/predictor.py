"""Prediction serving plane: `/predict` over device-resident rule tries.

The read half of the reference service at read QPS (ROADMAP item 1).
Three pieces:

- **Artifact cache** (:class:`ArtifactCache`): compiles a completed
  mine's rule set into the ops/rule_trie.py packed trie, keyed by
  ``(rule-set digest, geometry)`` — content-addressed, so a re-mine
  that changes the rules is a MISS by construction (staleness is a
  cache key, not a coherence protocol) — with LRU byte-bounding
  exactly like the fusion broker's fused-prep cache (entry cap + byte
  budget + never cache an entry over half the budget).  Build inputs
  resolve from a finished job uid (the store's rules payload) or a
  dataset fingerprint (the rescache entry service/resultcache.py keyed
  by it); pattern payloads (SPADE/SPAM mines) are lowered to rules by
  ``rule_trie.rules_from_patterns`` first.

- **Micro-batch broker** (:class:`PredictBroker`): the fusion broker's
  window machinery at serving latencies.  Concurrent requests against
  the SAME (digest, geometry, top-m) key park in a bounded window
  (milliseconds, not the mining broker's tens of ms) and dispatch as
  ONE scoring wave — request rows are the per-lane job tags, demuxed
  positionally on readback.  ``high`` priority makes the window due
  immediately (the `_ready_key` idea), a full window dispatches in the
  last joiner's thread, and disabling the window degrades every
  request to a solo launch (the bench's unfused baseline).  Row
  independence of the scoring kernel makes fusion byte-invariant (see
  DESIGN.md); the parity smoke pins it.

- **Serving surface** (:class:`Predictor`): the actor Master routes
  ``predict`` tasks to.  Validates the request, resolves the rule
  payload, gets-or-builds the artifact at the needed depth, rides the
  broker, and answers in the Questor prediction spelling (same entry
  shape, same exact host float division) so ``/predict`` is a drop-in
  fast path for ``/get/prediction``.  Read-path latency lands in the
  obsplane's second SLO signal class (``observe_predict`` ->
  ``/admin/slo``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from spark_fsm_tpu.ops import rule_trie
from spark_fsm_tpu.service import model, obsplane, usage
from spark_fsm_tpu.service.model import ServiceRequest, ServiceResponse, Status
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.obs import log_event

# ---------------------------------------------------------------------------
# Metrics — every family zero-seeded so a fresh scrape shows 0, not
# no-data (the obs_smoke no-orphan contract)
# ---------------------------------------------------------------------------

_REQS = obs.REGISTRY.counter(
    "fsm_predict_requests_total", "predict requests by outcome")
for _o in ("served", "failure", "no_rules"):
    _REQS.seed(outcome=_o)
_WAVES = obs.REGISTRY.counter(
    "fsm_predict_waves_total", "scoring waves launched, by fusion mode")
for _m in ("fused", "solo"):
    _WAVES.seed(mode=_m)
_WAVE_JOBS = obs.REGISTRY.histogram(
    "fsm_predict_wave_jobs", "requests fused per scoring wave",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)).seed()
_BUILDS = obs.REGISTRY.counter(
    "fsm_predict_artifact_builds_total", "rule-trie artifact compiles")
_STALE = obs.REGISTRY.counter(
    "fsm_predict_artifact_stale_rebuilds_total",
    "artifact rebuilds because the source's rule set changed (re-mine "
    "invalidation observed through the content-addressed key)")
_EVICTS = obs.REGISTRY.counter(
    "fsm_predict_artifact_evictions_total", "artifact cache LRU evictions")
_HITS = obs.REGISTRY.counter(
    "fsm_predict_artifact_cache_hits_total", "artifact cache hits")
_MISSES = obs.REGISTRY.counter(
    "fsm_predict_artifact_cache_misses_total", "artifact cache misses")


def _collect_metrics():
    cache = _CACHE
    hits, misses = _HITS.total(), _MISSES.total()
    ratio = hits / (hits + misses) if (hits + misses) else 0.0
    fused = solo = 0.0
    # fused ratio = share of REQUESTS served by a >=2-job wave; the
    # broker tallies jobs per mode under its own lock
    with _stats_lock:
        fused = float(_stats["fused_jobs"])
        solo = float(_stats["solo_jobs"])
    total_jobs = fused + solo
    now = time.time()
    age = 0.0
    entries = bytes_ = 0
    if cache is not None:
        with cache._lock:
            entries = len(cache._entries)
            bytes_ = cache._bytes
            if cache._entries:
                age = max(now - trie.built_ts
                          for trie, _ in cache._entries.values())
    return [
        ("fsm_predict_artifact_cache_hit_ratio", "gauge",
         "artifact cache hits / lookups (process lifetime)",
         [({}, round(ratio, 6))]),
        ("fsm_predict_fused_ratio", "gauge",
         "share of predict requests served by a fused (>=2 job) wave",
         [({}, round(fused / total_jobs, 6) if total_jobs else 0.0)]),
        ("fsm_predict_artifact_entries", "gauge",
         "resident rule-trie artifacts", [({}, entries)]),
        ("fsm_predict_artifact_bytes", "gauge",
         "resident rule-trie artifact bytes", [({}, bytes_)]),
        ("fsm_predict_artifact_age_seconds", "gauge",
         "age of the OLDEST resident artifact (staleness horizon: an "
         "artifact never outlives its digest, so age only measures how "
         "long a rule set has gone without re-mining)", [({}, round(age, 3))]),
    ]


obs.REGISTRY.register_collector("predictor", _collect_metrics)

_stats_lock = threading.Lock()
_stats = {"requests": 0, "served": 0, "failures": 0, "waves": 0,
          "fused_waves": 0, "fused_jobs": 0, "solo_jobs": 0,
          "stale_rebuilds": 0, "exec_s": 0.0}


def _bump(**kw) -> None:
    with _stats_lock:
        for k, v in kw.items():
            _stats[k] = _stats.get(k, 0) + v


# ---------------------------------------------------------------------------
# Config (mirrors fusion.configure: set_config pushes the section here)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_cfg = {
    "enabled": True,
    "window_ms": 2.0,
    "max_wave": 16,
    "topm": 8,
    "lanes_floor": 1024,
    "depth_floor": 16,
    "cache_entries": 8,
    "cache_bytes": 256 << 20,
}


def configure(pcfg) -> None:
    """Apply a parsed ``[predict]`` config section (config.set_config)."""
    global _CACHE
    with _cfg_lock:
        _cfg.update(
            enabled=bool(pcfg.enabled),
            window_ms=float(pcfg.window_ms),
            max_wave=int(pcfg.max_wave),
            topm=int(pcfg.topm),
            lanes_floor=int(pcfg.lanes_floor),
            depth_floor=int(pcfg.depth_floor),
            cache_entries=int(pcfg.artifact_entries),
            cache_bytes=int(pcfg.artifact_bytes),
        )
    _CACHE = ArtifactCache(int(pcfg.artifact_entries),
                           int(pcfg.artifact_bytes))


def _cfg_get(key: str):
    with _cfg_lock:
        return _cfg[key]


# ---------------------------------------------------------------------------
# Artifact cache
# ---------------------------------------------------------------------------

class ArtifactCache:
    """LRU rule-trie cache keyed ``(digest, depth geometry)`` with the
    fused-prep cache's byte-bounding rules: entry cap, byte budget, and
    never cache a single artifact over half the budget (one giant rule
    set must not flush the working set)."""

    def __init__(self, max_entries: int, max_bytes: int) -> None:
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, int], Tuple[rule_trie.RuleTrie, int]]" = OrderedDict()
        self._bytes = 0

    def get_or_build(self, digest: str, depth_need: int,
                     rules_provider: Callable[[], list],
                     lanes_floor: int) -> rule_trie.RuleTrie:
        key = (digest, int(depth_need))
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                _HITS.inc()
                return hit[0]
        _MISSES.inc()
        trie = rule_trie.build_trie(rules_provider(),
                                    lanes_floor=int(lanes_floor),
                                    depth_floor=int(depth_need))
        _BUILDS.inc()
        nbytes = trie.nbytes()
        if nbytes > self.max_bytes // 2:
            # oversized artifacts serve this request but are never
            # cached (the fused-prep half-budget rule)
            log_event("predict_artifact_uncacheable", bytes=nbytes,
                      budget=self.max_bytes, digest=digest[:12])
            return trie
        with self._lock:
            if key not in self._entries:
                self._entries[key] = (trie, nbytes)
                self._bytes += nbytes
            while (len(self._entries) > self.max_entries
                   or self._bytes > self.max_bytes):
                old_key, (_, old_bytes) = self._entries.popitem(last=False)
                self._bytes -= old_bytes
                _EVICTS.inc()
        return trie

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "resident": [
                    {"digest": k[0][:16], "depth": k[1],
                     "lanes": t.lanes, "F": t.F, "D": t.D,
                     "bytes": b, "rules": len(t.rules),
                     "age_s": round(time.time() - t.built_ts, 3)}
                    for k, (t, b) in self._entries.items()],
            }


_CACHE: Optional[ArtifactCache] = None


def _cache() -> ArtifactCache:
    global _CACHE
    if _CACHE is None:
        _CACHE = ArtifactCache(_cfg_get("cache_entries"),
                               _cfg_get("cache_bytes"))
    return _CACHE


# ---------------------------------------------------------------------------
# Micro-batch broker
# ---------------------------------------------------------------------------

class _Ticket:
    __slots__ = ("prefix", "priority", "event", "entries", "error",
                 "submit_t", "dispatch_t", "exec_s", "wave_jobs", "tag",
                 "tenant")

    def __init__(self, prefix: List[int], priority: str, tag: str,
                 tenant: str = "default") -> None:
        self.prefix = prefix
        self.priority = priority
        self.tag = tag
        self.tenant = tenant
        self.event = threading.Event()
        self.entries: Optional[List[dict]] = None
        self.error: Optional[BaseException] = None
        self.submit_t = time.monotonic()
        self.dispatch_t = self.submit_t
        self.exec_s = 0.0
        self.wave_jobs = 1


class _Group:
    __slots__ = ("key", "trie", "m", "tickets", "due_t")

    def __init__(self, key, trie, m: int, due_t: float) -> None:
        self.key = key
        self.trie = trie
        self.m = m
        self.tickets: List[_Ticket] = []
        self.due_t = due_t


class PredictBroker:
    """Windowed same-geometry wave fusion for predict requests.

    Groups key on ``(digest, F, D, m)`` — rows from different requests
    against the same artifact concatenate into one launch.  The window
    is per group from its FIRST joiner; ``high`` priority or a full
    window makes it due immediately.  Due groups dispatch in the
    scheduler thread (or, when full, in the last joiner's thread — no
    context switch on the hot path).  The scoring call itself is
    rule_trie.score_wave, so every row's bytes are independent of its
    wave-mates (DESIGN.md: integer-only kernel, per-row reductions).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._groups: Dict[tuple, _Group] = {}
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- scheduling ---------------------------------------------------------

    def _ensure_thread(self) -> None:
        # lazy like fusion's dispatcher pool: a boot that never predicts
        # never pays a thread
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="fsm-predict-window",
                                            daemon=True)
            self._stopped = False
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                now = time.monotonic()
                due = [k for k, g in self._groups.items() if g.due_t <= now]
                groups = [self._groups.pop(k) for k in due]
                if not groups:
                    nxt = min((g.due_t for g in self._groups.values()),
                              default=now + 0.05)
                    self._wake.wait(timeout=max(0.0005, nxt - now))
            for g in groups:
                self._run_group(g)

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
            leftovers = list(self._groups.values())
            self._groups.clear()
            self._wake.notify_all()
        for g in leftovers:
            self._run_group(g)

    # -- submission ---------------------------------------------------------

    def submit(self, trie: rule_trie.RuleTrie, prefix: List[int], m: int,
               priority: str, tag: str,
               tenant: str = "default") -> _Ticket:
        """Score one observed prefix; blocks until its wave lands.

        Returns the completed ticket — ``entries`` plus the window-wait
        and exec timings the read-path SLO wants split out.
        """
        window_s = max(0.0, float(_cfg_get("window_ms"))) / 1000.0
        max_wave = max(1, int(_cfg_get("max_wave")))
        t = _Ticket(prefix, priority, tag, tenant)
        if (not _cfg_get("enabled")) or window_s <= 0.0 or max_wave <= 1:
            g = _Group(None, trie, m, 0.0)
            g.tickets.append(t)
            self._run_group(g)
            if t.error is not None:
                raise t.error
            return t
        key = (trie.digest, trie.F, trie.D, int(m))
        run_now: Optional[_Group] = None
        with self._lock:
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _Group(
                    key, trie, int(m), time.monotonic() + window_s)
            g.tickets.append(t)
            if priority == "high":
                # a high-priority joiner makes the whole group due NOW —
                # riders already parked get the fast launch too (the
                # fusion broker's _ready_key posture)
                g.due_t = 0.0
            if len(g.tickets) >= max_wave or g.due_t <= time.monotonic():
                self._groups.pop(key, None)
                run_now = g
            else:
                self._ensure_thread()
                self._wake.notify_all()
        if run_now is not None:
            self._run_group(run_now)
        t.event.wait(timeout=30.0)
        if not t.event.is_set():
            raise TimeoutError("predict wave never dispatched")
        if t.error is not None:
            raise t.error
        return t

    # -- execution ----------------------------------------------------------

    def _run_group(self, g: _Group) -> None:
        n = len(g.tickets)
        t0 = time.monotonic()
        try:
            waves = rule_trie.score_wave(
                g.trie, [t.prefix for t in g.tickets], g.m)
            exec_s = time.monotonic() - t0
            mode = "fused" if n >= 2 else "solo"
            _WAVES.inc(mode=mode)
            _WAVE_JOBS.observe(float(n))
            _bump(waves=1, fused_waves=1 if n >= 2 else 0, exec_s=exec_s,
                  **{("fused_jobs" if n >= 2 else "solo_jobs"): n})
            log_event("predict_wave", jobs=n, mode=mode,
                      wave_ms=round(exec_s * 1000.0, 3),
                      tags=[t.tag for t in g.tickets])
            # per-rider attribution (service/usage.py): the wave is ONE
            # launch streaming the artifact's lanes once — launches and
            # lanes split across riders by largest-remainder (sums are
            # exact), wall split equally.  Riders have no JobControl,
            # so the cost folds straight into each rider's tenant.
            if usage.get() is not None:
                one = usage.split_integral(1, [1.0] * n)
                lanes = usage.split_integral(
                    int(getattr(g.trie, "lanes", 0) or 0), [1.0] * n)
                for i, t in enumerate(g.tickets):
                    usage.deposit_tenant(
                        t.tenant, launches=one[i],
                        traffic_units=lanes[i],
                        seconds_measured=exec_s / n)
            for i, t in enumerate(g.tickets):
                t.entries = waves[i]
                t.dispatch_t = t0
                t.exec_s = exec_s
                t.wave_jobs = n
                t.event.set()
        except BaseException as exc:
            for t in g.tickets:
                t.error = exc
                t.event.set()


_BROKER = PredictBroker()


def broker() -> PredictBroker:
    return _BROKER


# ---------------------------------------------------------------------------
# Serving surface
# ---------------------------------------------------------------------------

class Predictor:
    """``predict`` task handler: resolve rules, ride the broker, answer
    in the Questor prediction spelling."""

    def __init__(self, store) -> None:
        self.store = store
        self._src_lock = threading.Lock()
        self._src_digest: "OrderedDict[str, str]" = OrderedDict()

    # -- rule resolution ----------------------------------------------------

    def _resolve_payload(self, req: ServiceRequest
                         ) -> Tuple[Optional[str], Optional[str], str]:
        """-> (payload, kind, source key) or (None, error message, "")."""
        uid = req.uid
        fp = req.param("fingerprint")
        if uid:
            status = self.store.status(uid)
            if status is None:
                return None, "unknown uid", ""
            if status != Status.FINISHED:
                return None, "job not finished; results pending", ""
            payload = self.store.rules(uid)
            if payload is not None:
                return payload, "rules", f"uid:{uid}"
            payload = self.store.patterns(uid)
            if payload is not None:
                return payload, "patterns", f"uid:{uid}"
            return None, "no rules", ""
        if fp:
            from spark_fsm_tpu.service import resultcache

            algo = (req.param("algorithm") or "TSR_TPU").upper()
            # verified read + rules_digest cross-check: the artifact
            # cache below keys compiled tries on that digest, so never
            # build from bytes the digest does not vouch for.  Corrupt
            # entries are quarantined inside open_entry and report as
            # missing here (degrade, don't crash).
            opened = resultcache.open_entry(self.store, fp, algo,
                                            check_digest=True)
            if opened is None:
                return None, "no rescache entry for fingerprint", ""
            ent, _size = opened
            return (ent.get("payload") or "[]",
                    ent.get("kind") or "rules", f"fp:{fp}:{algo}")
        return None, "predict needs 'uid' (finished job) or 'fingerprint'", ""

    def _note_staleness(self, src: str, digest: str) -> None:
        with self._src_lock:
            prev = self._src_digest.get(src)
            if prev is not None and prev != digest:
                _STALE.inc()
                _bump(stale_rebuilds=1)
                log_event("predict_artifact_stale", source=src,
                          prev=prev[:12], now=digest[:12])
            self._src_digest[src] = digest
            self._src_digest.move_to_end(src)
            while len(self._src_digest) > 256:
                self._src_digest.popitem(last=False)

    # -- request handling ---------------------------------------------------

    def handle(self, req: ServiceRequest) -> ServiceResponse:
        t_start = time.monotonic()
        priority = (req.param("priority") or "normal").lower()
        if priority not in obsplane.PRIORITIES:
            _REQS.inc(outcome="failure")
            _bump(requests=1, failures=1)
            return model.response(
                req, Status.FAILURE,
                error=f"unknown priority {priority!r} "
                      f"(have: {', '.join(obsplane.PRIORITIES)})")
        # tenant threading (ISSUE 19): validated against the fairness
        # bounded vocabulary the same way obsplane.observe_job folds —
        # an unknown tenant reads as "default", never a failure (the
        # label space must stay bounded; a typo'd tenant still gets its
        # prediction)
        tenant = (req.param("tenant") or obsplane.DEFAULT_TENANT)
        if tenant not in obsplane.known_tenants():
            tenant = obsplane.DEFAULT_TENANT
        items_param = req.param("items")
        if items_param is None:
            _REQS.inc(outcome="failure")
            _bump(requests=1, failures=1)
            return model.response(
                req, Status.FAILURE,
                error="predict needs 'items' (comma-separated item ids "
                      "observed so far; empty allowed)")
        try:
            prefix = sorted({int(i) for i in items_param.split(",") if i})
        except ValueError:
            _REQS.inc(outcome="failure")
            _bump(requests=1, failures=1)
            return model.response(req, Status.FAILURE,
                                  error=f"bad 'items' value {items_param!r}")
        try:
            m = int(req.param("m") or _cfg_get("topm"))
        except ValueError:
            m = int(_cfg_get("topm"))
        m = max(1, min(m, 256))

        payload, kind, src = self._resolve_payload(req)
        if payload is None:
            outcome = "no_rules" if kind in ("no rules",
                                             "no rescache entry for "
                                             "fingerprint") else "failure"
            _REQS.inc(outcome=outcome)
            _bump(requests=1, failures=1)
            return model.response(req, Status.FAILURE, error=kind)
        digest = rule_trie.rules_digest(payload)
        self._note_staleness(src, digest)

        def rules_provider() -> list:
            if kind == "patterns":
                return rule_trie.rules_from_patterns(
                    model.deserialize_patterns(payload))
            return model.deserialize_rules(payload)

        depth_floor = int(_cfg_get("depth_floor"))
        depth_need = max(depth_floor, _next_pow2(max(1, len(prefix))))
        try:
            trie = _cache().get_or_build(digest, depth_need, rules_provider,
                                         _cfg_get("lanes_floor"))
            ticket = _BROKER.submit(trie, prefix, m, priority,
                                    tag=req.uid or src, tenant=tenant)
        except Exception as exc:
            _REQS.inc(outcome="failure")
            _bump(requests=1, failures=1)
            log_event("predict_failed", source=src, error=str(exc))
            return model.response(req, Status.FAILURE,
                                  error=f"predict failed: {exc}")
        e2e_s = time.monotonic() - t_start
        window_wait_s = max(0.0, ticket.dispatch_t - ticket.submit_t)
        # read-path SLO: the obsplane's second signal class
        obsplane.observe_predict(priority, e2e_s, window_wait_s,
                                 ticket.exec_s, tenant=tenant)
        entries = ticket.entries or []
        _REQS.inc(outcome="served")
        _bump(requests=1, served=1)
        return model.response(
            req, Status.FINISHED,
            predictions=json.dumps(entries),
            stats=json.dumps({
                "shape_key": f"predict:f{trie.F}d{trie.D}",
                "artifact_digest": digest[:16],
                "artifact_lanes": trie.lanes,
                "source": src,
                "fused": ticket.wave_jobs >= 2,
                "wave_jobs": ticket.wave_jobs,
                "m": m,
                "priority": priority,
                "tenant": tenant,
                "e2e_ms": round(e2e_s * 1000.0, 3),
                "window_wait_ms": round(window_wait_s * 1000.0, 3),
                "exec_ms": round(ticket.exec_s * 1000.0, 3),
            }))

    def stats(self) -> dict:
        with _stats_lock:
            s = dict(_stats)
        s["exec_s"] = round(s["exec_s"], 6)
        s["cache"] = _cache().snapshot()
        with _cfg_lock:
            s["config"] = dict(_cfg)
        return s

    def shutdown(self) -> None:
        _BROKER.shutdown()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p
