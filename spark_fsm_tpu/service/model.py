"""Request/response model + JSON serialization.

Mirrors the reference's model layer (SURVEY.md sec 2: ``ServiceRequest(
service, task, data: Map[String,String])``, ``FSMPattern`` = support +
itemset list, ``FSMRule`` = antecedent/consequent/support/confidence, job
statuses ``started -> dataset -> trained/finished`` plus ``failure``) with
plain dataclasses and json — the contracts are the reference's, the
implementation is not.
"""

from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Dict, List, Optional

from spark_fsm_tpu.utils.canonical import PatternResult, RuleResult


class Status:
    """Job lifecycle constants (the reference's ResponseStatus vocabulary)."""

    STARTED = "started"
    DATASET = "dataset"
    TRAINED = "trained"
    FINISHED = "finished"
    FAILURE = "failure"


@dataclasses.dataclass
class ServiceRequest:
    """``(service, task, data)`` request envelope.

    ``data`` carries the per-request knobs as a flat string map exactly
    like the reference: ``uid``, ``algorithm`` (any name in
    ``service/plugins.ALGORITHMS`` — the SPADE/SPAM pattern engines,
    the TSR rule engines, and ``AUTO`` for planner routing; an unknown
    name sheds a structured 400 listing the registry), ``source``,
    ``support``, ``k``, ``minconf``, ``maxgap``, ``maxwindow``, plus
    source-specific fields.
    """

    service: str
    task: str
    data: Dict[str, str]

    @property
    def uid(self) -> str:
        return self.data.get("uid", "")

    def param(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self.data.get(key, default)

    @staticmethod
    def fresh_uid() -> str:
        return uuid.uuid4().hex

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(text: str) -> "ServiceRequest":
        obj = json.loads(text)
        return ServiceRequest(
            service=obj.get("service", "fsm"),
            task=obj.get("task", ""),
            data={str(k): str(v) for k, v in obj.get("data", {}).items()},
        )


@dataclasses.dataclass
class ServiceResponse:
    service: str
    task: str
    data: Dict[str, str]
    status: str

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def response(req: ServiceRequest, status: str, **extra: str) -> ServiceResponse:
    data = {"uid": req.uid}
    data.update(extra)
    return ServiceResponse(req.service, req.task, data, status)


# ---------------------------------------------------------------------------
# Result serialization (patterns / rules)
# ---------------------------------------------------------------------------

def serialize_patterns(patterns: List[PatternResult]) -> str:
    """FSMPattern list -> JSON: [{"support": N, "itemsets": [[...], ...]}]."""
    return json.dumps([
        {"support": int(sup), "itemsets": [list(s) for s in pat]}
        for pat, sup in patterns
    ])


def deserialize_patterns(text: str) -> List[PatternResult]:
    return [
        (tuple(tuple(int(i) for i in s) for s in obj["itemsets"]), int(obj["support"]))
        for obj in json.loads(text)
    ]


def serialize_rules(rules: List[RuleResult]) -> str:
    """FSMRule list -> JSON with exact confidence (sup/supx kept integral)."""
    return json.dumps([
        {
            "antecedent": list(x),
            "consequent": list(y),
            "support": int(sup),
            "antecedent_support": int(supx),
            "confidence": (int(sup) / int(supx)) if supx else 0.0,
        }
        for x, y, sup, supx in rules
    ])


def deserialize_rules(text: str) -> List[RuleResult]:
    return [
        (tuple(int(i) for i in obj["antecedent"]),
         tuple(int(i) for i in obj["consequent"]),
         int(obj["support"]), int(obj["antecedent_support"]))
        for obj in json.loads(text)
    ]
