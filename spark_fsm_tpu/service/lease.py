"""Lease-fenced multi-replica job ownership — the scale-out unlock.

PR 5's journal recovery documented its own ceiling: liveness was
inferred from a process-local incarnation id, so exactly ONE service
instance could own a store ("one store per instance until a
lease/heartbeat exists").  This module is that lease.  N replicas share
one Redis namespace safely; the failure of any replica degrades
CAPACITY (its jobs are adopted after a bounded TTL) instead of
CORRECTNESS (no double-commit, ever) — the reference's actor-routed
orchestration generalized across processes, the partitioned-worker
shape of DIMSpan/the parallel-SPM survey applied to job ownership.

The protocol, in store verbs the MiniRedis test server also speaks:

- **Acquire** (admission): ``SET fsm:lease:{uid} {replica,token} PX ttl
  NX``.  The FENCING TOKEN comes from ``INCR fsm:lease:token`` — one
  monotonic sequence per store, so any later acquisition of the same
  uid (adoption after expiry, work steal) holds a STRICTLY larger
  token than every earlier one.
- **Renew**: a per-replica heartbeat thread re-arms every held lease
  with ``PEXPIRE`` at ``lease_ttl/3``.  Why /3: two full renewal
  attempts can fail outright before the TTL lapses, so a single slow
  store round-trip never costs a healthy replica its leases.
- **Fence**: every journal/checkpoint/result write path consults the
  local lease record first (one dict read while the TTL is provably
  live — the adopter must outwait STORE expiry, which postdates our
  conservative local deadline) and verifies against the store once the
  local record lapses.  A superseded holder raises
  :class:`~spark_fsm_tpu.utils.jobctl.JobLeaseLost` and its writes are
  REFUSED — a replica that wakes from a GC pause/SIGSTOP after its TTL
  cannot double-commit against the adopting replica's run.
- **Release** (terminal): compare-and-delete — GET, compare our token,
  DEL.  The GET→DEL window is the classic CAD caveat; it is bounded by
  one round-trip against a TTL thousands of times longer, and the
  fencing token backstops the residual race (a wrongly deleted lease
  only ever ACCELERATES adoption, never permits double-commit).
- **Steal** (two-phase claim): each replica mirrors its QUEUED jobs as
  ``fsm:admission:{replica}:{uid}`` markers.  An idle replica claims a
  loaded peer's marker with ``DEL`` — the store's atomic "exactly one
  caller sees 1" arbiter — then takes the lease over with a fresh
  (larger) token and resubmits the journaled request through its own
  admission path.  The victim's worker runs the SAME ``DEL`` at
  dequeue: whoever wins the delete owns the job, the loser walks away,
  so a queued job is never run twice.  A thief that dies between claim
  and resubmit leaves a journal orphan whose lease expires — the
  periodic recovery pass (below) re-adopts it; nothing is ever lost.
- **Adopt** (boot + periodic recovery): ``recover_orphans`` treats a
  foreign journal entry as dead ONLY once its lease has expired, and
  adoption itself is an NX acquire — two replicas booting into the same
  wreckage race the atomic SET, exactly one adopts each orphan.

Fault sites: ``lease.acquire`` / ``lease.renew`` / ``lease.steal``
(utils/faults KNOWN_SITES) wrap the protocol's store round-trips;
the lease layer reads raw keys via ``store.peek`` so chaos drills on
``store.get`` never alias onto lease verification.

Disabled (``[cluster] enabled = false``, the default) costs the
single-replica deployment nothing: no manager is built and every guard
in the Miner is one ``is None`` check.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from spark_fsm_tpu.service import obsplane
from spark_fsm_tpu.utils import envelope, faults, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event

_HELD = obs.REGISTRY.gauge(
    "fsm_lease_held", "job leases this replica currently holds")
_PEERS = obs.REGISTRY.gauge(
    "fsm_replica_peers", "peer replicas with a live heartbeat record")
_ACQUIRE_TOTAL = (obs.REGISTRY.counter(
    "fsm_lease_acquired_total", "lease acquisition attempts, by outcome")
    .seed(outcome="ok").seed(outcome="held").seed(outcome="error"))
_RENEW_TOTAL = (obs.REGISTRY.counter(
    "fsm_lease_renewals_total", "heartbeat lease renewals, by outcome")
    .seed(outcome="ok").seed(outcome="lost").seed(outcome="error"))
_REACQUIRED_TOTAL = obs.REGISTRY.counter(
    "fsm_lease_reacquired_total",
    "expired-but-unclaimed leases seamlessly reacquired by their holder")
_LOST_TOTAL = obs.REGISTRY.counter(
    "fsm_lease_lost_total",
    "leases this replica lost (expired unrecoverably or superseded)")
_FENCE_REJECTED_TOTAL = obs.REGISTRY.counter(
    "fsm_lease_fence_rejections_total",
    "store writes refused because the writer's lease was superseded — "
    "each one is a double-commit that did NOT happen")
_STEAL_TOTAL = (obs.REGISTRY.counter(
    "fsm_steal_attempts_total", "work-steal claims on peers' queued "
    "jobs, by outcome").seed(outcome="stolen").seed(outcome="lost_race")
    .seed(outcome="error"))
_VICTIM_DROPS_TOTAL = obs.REGISTRY.counter(
    "fsm_steal_victim_drops_total",
    "queued jobs this replica dropped at dequeue because a peer had "
    "already claimed them (the victim side of a successful steal)")
_HEARTBEATS_TOTAL = obs.REGISTRY.counter(
    "fsm_replica_heartbeats_total",
    "heartbeat records published by this replica")

_TOKEN_KEY = "fsm:lease:token"


class LeaseHeld(RuntimeError):
    """Acquisition refused: another replica holds a live lease on the
    uid.  The admission layer maps it to the same 409 surface as a
    process-local live-uid conflict — the job IS live, just elsewhere."""

    def __init__(self, uid: str, holder: Optional[str]):
        self.holder = holder
        super().__init__(
            f"uid {uid!r} is leased by replica {holder or 'unknown'!r}; "
            "resubmitting would race a live job — wait for a terminal "
            "status or use a new uid")


class LeaseUnavailable(RuntimeError):
    """The lease protocol itself failed (store down, injected fault):
    the submit cannot be made safe, so it is refused with HTTP 503
    BEFORE any store trace of the uid exists."""


class _Held:
    """This replica's record of one held lease.  ``expires`` is a LOCAL
    monotonic deadline computed from the instant just before the store
    round-trip, so it is always <= the store's own expiry — while
    ``clock() < expires`` no adopter can exist yet and the fence is one
    dict read."""

    __slots__ = ("uid", "token", "expires", "ctl", "lost")

    def __init__(self, uid: str, token: int, expires: float):
        self.uid = uid
        self.token = token
        self.expires = expires
        self.ctl: Optional[jobctl.JobControl] = None
        self.lost = False


class LeaseManager:
    """One per service replica: owns the replica id, the held-lease
    table, and the heartbeat thread (renewal + heartbeat record +
    steal scan + periodic orphan recovery)."""

    def __init__(self, store, replica_id: Optional[str] = None,
                 lease_ttl_s: float = 10.0,
                 heartbeat_s: Optional[float] = None,
                 steal: bool = True,
                 recover_every_s: Optional[float] = None,
                 clock=time.monotonic) -> None:
        if lease_ttl_s <= 0:
            raise ValueError(f"lease_ttl_s must be > 0 (got {lease_ttl_s})")
        self._store = store
        self.replica_id = replica_id or uuid.uuid4().hex[:12]
        self.lease_ttl_s = float(lease_ttl_s)
        self._ttl_ms = max(1, int(self.lease_ttl_s * 1000))
        # ttl/3 so two consecutive renewal failures still leave one
        # attempt before the TTL lapses (DESIGN.md "Lease protocol").
        # None = the default cadence; 0 = MANUAL-TICK mode (no thread —
        # tests drive tick()/renew_all() deterministically)
        self.heartbeat_s = (self.lease_ttl_s / 3.0 if heartbeat_s is None
                            else float(heartbeat_s))
        self.steal_enabled = bool(steal)
        self.recover_every_s = (float(recover_every_s) if recover_every_s
                                else self.lease_ttl_s)
        self._clock = clock
        # store-outage guard (service/storeguard.py): attached by
        # storeguard.install when [storeguard] is enabled — None keeps
        # every outage hook below at one `is None` read
        self._guard = None
        self._lock = threading.Lock()
        # serializes _verify: the heartbeat's renew_all and a worker's
        # stale fence() may race the expired-unclaimed NX reacquire —
        # unserialized, the loser of the replica's OWN two-thread race
        # would read "claimed by someone" and spuriously self-fence
        self._verify_lock = threading.Lock()
        # set during shutdown drain: stop pulling NEW work (steal,
        # periodic adoption) while held leases keep renewing so the
        # draining jobs stay fenced-safe to their end
        self._quiesced = False
        # scale-down drain (ISSUE 13): advertised in the heartbeat so
        # peers steal our backlog and stop counting our capacity
        self._draining = False
        # peers cache refreshed on the heartbeat cadence: peer_free_total
        # sits on the 429 shed path, and a shed storm must not turn into
        # a KEYS storm against the shared store
        self._peers_cache: tuple = (-1e18, [])
        self._held: Dict[str, _Held] = {}
        self._miner = None  # set by start(); duck-typed (Miner)
        self._recover: Optional[Callable[[], object]] = None
        self._next_recover = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, store, ccfg) -> "LeaseManager":
        return cls(store,
                   replica_id=ccfg.replica_id or None,
                   lease_ttl_s=ccfg.lease_ttl_s,
                   heartbeat_s=ccfg.heartbeat_s or None,
                   steal=ccfg.steal,
                   recover_every_s=ccfg.recover_every_s or None)

    # ------------------------------------------------------------- keys

    @staticmethod
    def _lease_key(uid: str) -> str:
        return f"fsm:lease:{uid}"

    def _adm_key(self, uid: str) -> str:
        return f"fsm:admission:{self.replica_id}:{uid}"

    @property
    def _hb_key(self) -> str:
        return f"fsm:replica:{self.replica_id}"

    def _payload(self, token: int) -> str:
        return json.dumps({"replica": self.replica_id, "token": token})

    @staticmethod
    def _parse(raw: Optional[str]) -> dict:
        """Envelope-aware tolerant decode: journal intents and heartbeat
        records now ride checksum envelopes (utils/envelope.py); legacy
        bare JSON still parses, corrupt bytes read as absent ({}) — the
        lease plane's degradation for a rotten record is simply to not
        trust it."""
        if not raw:
            return {}
        payload, _verdict = envelope.unwrap(raw)
        if payload is None:
            return {}
        try:
            out = json.loads(payload)
            return out if isinstance(out, dict) else {}
        except ValueError:
            return {}

    def _journal_ours(self, uid: str) -> bool:
        """Does the journal intent still name THIS replica?  The
        reacquire gate: a lease that expired *unclaimed* may be
        re-taken only while the intent is ours — an adopter/thief
        rewrites the journal under its own replica id at resubmit, and
        every terminal path clears it BEFORE releasing the lease, so a
        stale holder that slept through the entire adopted run (lease
        long released again) still cannot reacquire and double-commit."""
        entry = self._parse(self._store.peek(f"fsm:journal:{uid}"))
        return entry.get("replica") == self.replica_id

    def _set_held(self, uid: str, token: int, expires: float) -> _Held:
        with self._lock:
            h = self._held.get(uid)
            if h is None:
                h = self._held[uid] = _Held(uid, token, expires)
            else:
                h.token, h.expires, h.lost = token, expires, False
            _HELD.set(len(self._held))
            return h

    def _mark_lost(self, h: _Held, why: str) -> None:
        if h.lost:
            return
        h.lost = True
        _LOST_TOTAL.inc()
        jobctl.fence_lost(h.ctl)
        # tombstone the uid on the trace spine too: a stale holder's
        # buffered spans must never flush onto the adopter's timeline
        obsplane.mark_fenced(h.uid)
        log_event("lease_lost", uid=h.uid, token=h.token, why=why,
                  replica=self.replica_id)
        # explicit trace id: the heartbeat thread carries no span context
        with obs.span("lifecycle.fenced", trace_id=h.uid, token=h.token,
                      why=why, replica=self.replica_id):
            pass

    # --------------------------------------------------------- protocol

    def acquire(self, uid: str) -> int:
        """Acquire (or re-enter) the lease for ``uid``; returns the
        fencing token.  Raises :class:`LeaseHeld` when a peer holds a
        live lease (the 409 surface) and :class:`LeaseUnavailable` when
        the protocol itself failed (the 503 surface — zero store trace
        of the uid exists yet)."""
        h = self._held.get(uid)
        if h is not None and not h.lost:
            # re-entrant: adoption/steal acquired before the resubmit
            if self._clock() < h.expires:
                return h.token
            try:
                if self._verify(h):
                    return h.token
            except Exception:
                pass  # fall through to a fresh acquisition
        try:
            faults.fault_site("lease.acquire", uid=uid)
            t0 = self._clock()
            token = int(self._store.incr(_TOKEN_KEY))
            key = self._lease_key(uid)
            ok = self._store.set_px(key, self._payload(token), self._ttl_ms,
                                    nx=True)
            holder = None
            if not ok:
                raw = self._store.peek(key)
                if raw is None:  # expired between the NX and this read
                    ok = self._store.set_px(key, self._payload(token),
                                            self._ttl_ms, nx=True)
                else:
                    holder = self._parse(raw).get("replica")
        except Exception as exc:
            _ACQUIRE_TOTAL.inc(outcome="error")
            raise LeaseUnavailable(
                f"lease acquisition for uid {uid!r} failed: {exc}") from exc
        if not ok:
            _ACQUIRE_TOTAL.inc(outcome="held")
            raise LeaseHeld(uid, holder)
        _ACQUIRE_TOTAL.inc(outcome="ok")
        self._set_held(uid, token, t0 + self.lease_ttl_s)
        return token

    def attach(self, uid: str, ctl: Optional[jobctl.JobControl]) -> None:
        """Bind the job's control entry so a heartbeat-detected loss
        self-fences the job at its next safe point.  Binds the OBJECT,
        not the uid: in multi-replica tests two miners in one process
        may register the same uid and the flag must land on the
        incarnation that lost its lease."""
        h = self._held.get(uid)
        if h is not None:
            h.ctl = ctl

    def _verify(self, h: _Held) -> bool:
        """One store round-trip re-proving ownership of ``h`` and
        re-arming its TTL.  False = lost (marked, control entry
        fenced).  Raises on store failure — the caller decides whether
        an UNVERIFIABLE lease is survivable (heartbeat: yes, until the
        TTL lapses) or not (a stale fence check: no)."""
        with self._verify_lock:
            return self._verify_locked(h)

    def _verify_locked(self, h: _Held) -> bool:
        faults.fault_site("lease.renew", uid=h.uid)
        key = self._lease_key(h.uid)
        t0 = self._clock()
        raw = self._store.peek(key)
        if raw is not None:
            if int(self._parse(raw).get("token", -1)) == h.token:
                if self._store.pexpire(key, self._ttl_ms):
                    h.expires = t0 + self.lease_ttl_s
                    return True
                raw = None  # expired between the read and the renew
            else:
                self._mark_lost(h, "superseded")
                return False
        if raw is None:
            # expired but UNCLAIMED: one atomic NX reacquire decides
            # between seamless continuation and self-fencing — gated on
            # the journal intent still being OURS (an absent/foreign
            # intent means the job was adopted, and possibly already
            # finished, elsewhere; "the lease key is free again" is NOT
            # proof nobody superseded us in between)
            if self._journal_ours(h.uid):
                token = int(self._store.incr(_TOKEN_KEY))
                if self._store.set_px(key, self._payload(token),
                                      self._ttl_ms, nx=True):
                    h.token = token
                    h.expires = t0 + self.lease_ttl_s
                    h.lost = False
                    _REACQUIRED_TOTAL.inc()
                    log_event("lease_reacquired", uid=h.uid, token=token)
                    return True
                self._mark_lost(h, "expired_and_claimed")
                return False
            self._mark_lost(h, "expired_and_disowned")
            return False
        self._mark_lost(h, "superseded")
        return False

    def fence(self, uid: str) -> None:
        """The write-path guard: raise
        :class:`~spark_fsm_tpu.utils.jobctl.JobLeaseLost` unless this
        replica can prove it still owns ``uid``.  One dict read while
        the local TTL is live; a store verification once it lapses.
        Uids never leased here (stream pushes) pass untouched."""
        h = self._held.get(uid)
        if h is None:
            return
        if not h.lost and self._clock() < h.expires:
            return
        if not h.lost:
            try:
                if self._verify(h):
                    return
            except Exception as exc:
                if (self._guard is not None
                        and self._guard.note_error(exc)):
                    # PROVEN store outage: the write this fence guards
                    # is about to ride the spool, whose replay gate
                    # re-proves the token before anything lands — allow
                    # it (stall semantics), don't fence
                    return
                # unverifiable at a point where the TTL may already have
                # lapsed: refusing the write is the only safe answer
                self._mark_lost(h, f"unverifiable: {exc}")
        _FENCE_REJECTED_TOTAL.inc()
        raise jobctl.JobLeaseLost(
            uid, "its replica lease expired or was superseded; refusing "
                 "the write to avoid double-commit")

    def attach_guard(self, guard) -> None:
        """Bind the store-outage guard (service/storeguard.py): renewal
        failures past the TTL during a PROVEN store outage stall the
        job at its next safe point instead of fencing it."""
        self._guard = guard

    def renew_all(self) -> None:
        """Heartbeat renewal of every held lease.  A renewal FAILURE is
        survivable until the TTL lapses (the job keeps running); past
        it the job is fenced at its next safe point — unless the
        storeguard probe proves the store GLOBALLY unreachable, in
        which case the job STALLS there instead (frontier kept in
        memory + spool) and the journal-gated NX reacquire decides its
        fate when the store returns.  A replica that cannot prove the
        outage (store answers the probe) fences as before: when in
        doubt, fence."""
        for h in list(self._held.values()):
            if h.lost:
                continue
            try:
                if self._verify(h):
                    _RENEW_TOTAL.inc(outcome="ok")
                else:
                    _RENEW_TOTAL.inc(outcome="lost")
            except Exception as exc:
                _RENEW_TOTAL.inc(outcome="error")
                if self._clock() >= h.expires:
                    if (self._guard is not None
                            and self._guard.stall_job(h.ctl, h.uid)):
                        continue
                    self._mark_lost(h, f"renewal failed past TTL: {exc}")

    def settle_for_failure(self, uid: str) -> bool:
        """May this replica durably record ``uid``'s failure?  True for
        never-leased uids and live leases.  For a lost/expired lease,
        ONE atomic NX reacquire decides: success means nobody adopted
        (safe to settle durably — a client polling the uid deserves the
        terminal status); refusal means the adopter owns the uid's keys
        and this replica's failure must stay local."""
        h = self._held.get(uid)
        if h is None:
            return True
        if not h.lost and self._clock() < h.expires:
            return True
        key = self._lease_key(uid)
        try:
            raw = self._store.peek(key)
            if raw is not None:
                if int(self._parse(raw).get("token", -1)) == h.token:
                    return True
                _FENCE_REJECTED_TOTAL.inc()
                log_event("lease_failure_write_fenced", uid=uid,
                          replica=self.replica_id)
                return False
            # same reacquire gate as _verify: only settle an expired
            # lease while the journal intent is still OURS — otherwise
            # an adopter ran (and may have finished + released) and the
            # uid's keys are its, not ours
            if self._journal_ours(uid):
                t0 = self._clock()
                token = int(self._store.incr(_TOKEN_KEY))
                if self._store.set_px(key, self._payload(token),
                                      self._ttl_ms, nx=True):
                    self._set_held(uid, token, t0 + self.lease_ttl_s)
                    return True
        except Exception as exc:
            log_event("lease_settle_unverifiable", uid=uid, error=str(exc))
        _FENCE_REJECTED_TOTAL.inc()
        return False

    def reacquire_for_spool(self, uid: str, token: Optional[int]) -> bool:
        """The write-behind spool's replay gate (service/storeguard.py):
        may the spooled writes for ``uid`` — taken under fencing
        ``token`` before/during the outage — land now?

        True in exactly two cases: the store lease STILL carries our
        token (the outage was shorter than the TTL), or the lease
        expired UNCLAIMED and the journal intent still names this
        replica — then one atomic NX re-take under the SAME token
        resumes the epoch (nobody else ever held the uid in between,
        so token monotonicity is preserved: same holder, same token).
        Any other state means the lease was legitimately taken during
        the outage — the adopter owns the uid's keys and the replay
        must be REFUSED (the PR 8 no-double-commit invariant, verbatim).
        Transport errors propagate (the guard re-enters DOWN and keeps
        the spool)."""
        if token is None:
            return False
        key = self._lease_key(uid)
        with self._verify_lock:
            t0 = self._clock()
            raw = self._store.peek(key)
            if raw is not None:
                if int(self._parse(raw).get("token", -1)) == int(token):
                    if self._store.pexpire(key, self._ttl_ms):
                        h = self._held.get(uid)
                        if h is not None and h.token == token:
                            h.expires = t0 + self.lease_ttl_s
                            h.lost = False
                        return True
                    raw = None  # expired between the read and the renew
                else:
                    _FENCE_REJECTED_TOTAL.inc()
                    h = self._held.get(uid)
                    if h is not None and h.token == token:
                        self._mark_lost(h, "outage_superseded")
                    return False
            if not self._journal_ours(uid):
                # adopted (and possibly finished + settled) elsewhere
                # during the outage — the uid's keys are the adopter's
                _FENCE_REJECTED_TOTAL.inc()
                h = self._held.get(uid)
                if h is not None and h.token == token:
                    self._mark_lost(h, "outage_adopted")
                return False
            if self._store.set_px(key, self._payload(int(token)),
                                  self._ttl_ms, nx=True):
                h = self._held.get(uid)
                if h is not None:
                    h.token = int(token)
                    h.expires = t0 + self.lease_ttl_s
                    h.lost = False
                _REACQUIRED_TOTAL.inc()
                log_event("lease_reacquired_for_replay", uid=uid,
                          token=token)
                return True
            _FENCE_REJECTED_TOTAL.inc()
            h = self._held.get(uid)
            if h is not None and h.token == token:
                self._mark_lost(h, "outage_claimed")
            return False

    def release_token(self, uid: str, token: int) -> None:
        """Compare-and-delete by EXPLICIT token — the spool replay's
        cleanup for a job that settled locally during the outage (its
        normal release already ran as a store-side no-op, so no
        ``_held`` record exists to release through)."""
        key = self._lease_key(uid)
        try:
            if int(self._parse(self._store.peek(key)).get("token", -1)) \
                    == int(token):
                self._store.delete(key)
        except Exception as exc:
            log_event("lease_release_failed", uid=uid, error=str(exc))

    def release(self, uid: str) -> None:
        """Terminal-status release: compare-and-delete (best effort —
        the TTL reaps anything this misses, and the fencing token keeps
        even a misdelete harmless)."""
        with self._lock:
            h = self._held.pop(uid, None)
            _HELD.set(len(self._held))
        if h is None:
            return
        key = self._lease_key(uid)
        try:
            if int(self._parse(self._store.peek(key)).get("token", -1)) \
                    == h.token:
                self._store.delete(key)
        except Exception as exc:
            log_event("lease_release_failed", uid=uid, error=str(exc))

    def forget(self, uid: str) -> None:
        """Drop the local record WITHOUT touching the store — the victim
        side of a steal (the thief owns the store lease now)."""
        with self._lock:
            self._held.pop(uid, None)
            _HELD.set(len(self._held))

    def attached_ctl(self, uid: str) -> Optional[jobctl.JobControl]:
        """The control object bound at attach time — the victim-drop
        paths release THIS object (jobctl.release_entry), never the
        uid, which in an in-process multi-replica topology may already
        map to the thief's live entry."""
        h = self._held.get(uid)
        return None if h is None else h.ctl

    def held_uids(self) -> List[str]:
        with self._lock:
            return sorted(self._held)

    def token_of(self, uid: str) -> Optional[int]:
        h = self._held.get(uid)
        return None if h is None else h.token

    def is_lost(self, uid: str) -> bool:
        """True while the local record says the uid's lease was lost —
        the trace spine's cheap pre-check (one dict read) before it
        even builds a chunk."""
        h = self._held.get(uid)
        return h is not None and h.lost

    # ------------------------------------------------- adoption (recovery)

    def adopt_expired(self, uid: str) -> bool:
        """Boot/periodic recovery's adoption gate: True only when the
        orphan's lease has EXPIRED and this replica won the atomic NX
        re-acquisition.  A live lease means the job is merely running on
        a peer — PR 5's recovery would have called it dead and
        double-submitted it; this check is the multi-replica fix."""
        key = self._lease_key(uid)
        try:
            if self._store.peek(key) is not None:
                return False  # live on some replica (possibly us)
            t0 = self._clock()
            token = int(self._store.incr(_TOKEN_KEY))
            if not self._store.set_px(key, self._payload(token),
                                      self._ttl_ms, nx=True):
                return False  # another recovering replica won the race
        except Exception as exc:
            log_event("lease_adopt_failed", uid=uid, error=str(exc))
            return False
        self._set_held(uid, token, t0 + self.lease_ttl_s)
        log_event("lease_adopted", uid=uid, token=token,
                  replica=self.replica_id)
        return True

    # ------------------------------------------------------ work stealing

    def publish_admission(self, uid: str) -> None:
        """Mirror a QUEUED job into this replica's admission namespace —
        the steal scan's menu."""
        self._store.set(self._adm_key(uid), "1")

    def retract_admission(self, uid: str) -> bool:
        """Atomically claim the queued job for LOCAL execution (the
        worker's dequeue step).  False = a thief already claimed it."""
        return self._store.delete(self._adm_key(uid)) >= 1

    def retract_admission_deferred(self, uid: str, guard) -> None:
        """Outage spelling of :meth:`retract_admission`: spool the
        marker DEL through the storeguard so it lands at replay — the
        marker-key layout stays this class's private knowledge.  A
        post-heal thief racing the replayed DEL loses either way:
        whoever loses the arbiter is fenced by token."""
        guard.delete(uid, self._adm_key(uid))

    def admission_claimed(self, uid: str) -> bool:
        """Has a thief already claimed this queued job's marker?  The
        DRAIN loop's poll: with the queue paused, the worker-side
        victim drop never runs, so the drain reaps stolen entries
        itself.  Read-only (peek) — the atomic arbiter stays the DEL."""
        return self._store.peek(self._adm_key(uid)) is None

    def stolen_from_us(self, uid: str) -> None:
        """Victim-side bookkeeping when retract_admission lost the DEL
        race: drop local state, count, leave the thief's journal/lease
        untouched."""
        self.forget(uid)
        _VICTIM_DROPS_TOTAL.inc()
        log_event("job_stolen_from_us", uid=uid, replica=self.replica_id)
        with obs.span("lifecycle.stolen", trace_id=uid, side="victim",
                      replica=self.replica_id):
            pass

    def publish_heartbeat(self) -> None:
        """Advertise this replica's load (PX = lease TTL, so a dead
        replica's record vanishes with its leases).  ``free`` — worker
        slots not covered by running or queued work — is what peers'
        Retry-After estimators and steal scans read.  The record also
        piggybacks a COMPACT metric snapshot (held leases, lifetime
        sheds/acquire/loss counters, EWMA job wall) so any replica can
        serve the aggregated cluster view (/admin/cluster,
        fsm_cluster_*) without touching its peers directly."""
        m = self._miner
        self._store.set_px(self._hb_key, envelope.wrap(json.dumps({
            "replica": self.replica_id,
            "queued": m.queue_size() if m is not None else 0,
            "running": m.running_count() if m is not None else 0,
            "workers": m.worker_count() if m is not None else 0,
            # the ONE derivation of free capacity — also the steal
            # scan's budget (Miner.idle_capacity).  A DRAINING replica
            # advertises zero: its slots are leaving the fleet.
            "free": (0 if self._draining else
                     m.idle_capacity() if m is not None else 0),
            # whether this replica WILL actually steal: peers' 429
            # Retry-After hints must not point at a steal path that is
            # disabled or quiescing for shutdown
            "steal": bool(self.steal_enabled and not self._quiesced),
            # scale-down drain state (ISSUE 13): peers steal a draining
            # replica's queue and the autoscaler excludes it from the
            # fleet's capacity arithmetic
            "draining": bool(self._draining),
            # per-tenant queued depths (fairness scheduler; {} without
            # one) — the /admin/cluster multi-tenant load view
            "tenants": (getattr(m, "tenant_depths", dict)()
                        if m is not None else {}),
            # in-flight coalescing-leader dataset fingerprints (ROADMAP
            # 2c; [] without the result-reuse tier): peers consult this
            # before admitting a duplicate cold mine, bounded so the
            # heartbeat record stays compact
            "fps": (list(getattr(m, "inflight_fps", list)())[:32]
                    if m is not None else []),
            # metric snapshot (ISSUE 9): lifetime counters are summed
            # by readers; a dead replica's contribution vanishes with
            # its record — the aggregate view is of LIVE replicas
            "held": len(self._held),
            "sheds": int(m.sheds_total()) if m is not None else 0,
            "ewma_s": (round(m.wall_ewma(), 4)
                       if m is not None and m.wall_ewma() is not None
                       else None),
            # compact per-replica SLO digest (ISSUE 14 satellite): the
            # worst local e2e p99 + sample count — the autoscale leader
            # scales on the FLEET max of these instead of its own
            # (possibly idle, therefore blind) local window
            "slo": obsplane.slo_digest(),
            # lifetime successful admissions (ISSUE 15 satellite): the
            # autoscale leader differentiates the fleet sum of these
            # for the predictive rate-derivative scale-up signal
            "adm": (int(getattr(m, "admitted_total", lambda: 0)())
                    if m is not None else 0),
            "acq": int(_ACQUIRE_TOTAL.total()),
            "lost": int(_LOST_TOTAL.total()),
            # degraded-topology gossip (ISSUE 20, service/meshguard.py):
            # {"epoch", "dead"} so peers converge on the fleet-max
            # topology epoch and the union dead-row set; None when the
            # guard is off
            "mesh": self._mesh_payload(),
            "ts": round(time.time(), 3)})), self._ttl_ms)
        _HEARTBEATS_TOTAL.inc()

    @staticmethod
    def _mesh_payload() -> Optional[dict]:
        try:
            from spark_fsm_tpu.service import meshguard
            g = meshguard.get()
            return None if g is None else g.heartbeat_payload()
        except Exception:
            return None

    def peers(self, max_age_s: Optional[float] = None) -> List[dict]:
        """Live peer heartbeat records.  ``max_age_s`` serves a cached
        scan no older than that — the store walk must stay OFF hot
        paths (the 429 shed estimator, scrape-time collectors); None
        forces a fresh cursor scan (the heartbeat tick / steal path)."""
        if max_age_s is not None:
            ts, cached = self._peers_cache
            if self._clock() - ts < max_age_s:
                return cached
        out = []
        for key in self._store.scan_iter("fsm:replica:", count=256):
            rid = key[len("fsm:replica:"):]
            if rid == self.replica_id:
                continue
            p = self._parse(self._store.peek(key))
            if p:
                out.append(p)
        _PEERS.set(len(out))
        self._peers_cache = (self._clock(), out)
        return out

    def cluster_view(self, max_age_s: Optional[float] = None) -> dict:
        """The /admin/cluster body (and the fsm_cluster_* collector's
        input): this replica's live row + every un-expired peer
        heartbeat, with cluster totals.  Peers come from the heartbeat-
        cadence cache by default — any replica can serve this under a
        scrape storm without driving store scans."""
        m = self._miner
        self_row = {
            "replica": self.replica_id, "self": True,
            "queued": m.queue_size() if m is not None else 0,
            "running": m.running_count() if m is not None else 0,
            "workers": m.worker_count() if m is not None else 0,
            "free": (0 if self._draining else
                     m.idle_capacity() if m is not None else 0),
            "steal": bool(self.steal_enabled and not self._quiesced),
            "draining": bool(self._draining),
            "tenants": (getattr(m, "tenant_depths", dict)()
                        if m is not None else {}),
            "held": len(self._held),
            "sheds": int(m.sheds_total()) if m is not None else 0,
            "ewma_s": (round(m.wall_ewma(), 4)
                       if m is not None and m.wall_ewma() is not None
                       else None),
            "slo": obsplane.slo_digest(),
            "adm": (int(getattr(m, "admitted_total", lambda: 0)())
                    if m is not None else 0),
            "acq": int(_ACQUIRE_TOTAL.total()),
            "lost": int(_LOST_TOTAL.total()),
        }
        try:
            peers = self.peers(
                max_age_s=(max_age_s if max_age_s is not None
                           else max(self.heartbeat_s, 1.0)))
        except Exception:
            peers = []
        rows = [self_row] + [dict(p) for p in peers]

        def tot(key: str) -> int:
            return sum(int(r.get(key) or 0) for r in rows)

        totals = {"replicas": len(rows), "queued": tot("queued"),
                  "running": tot("running"), "workers": tot("workers"),
                  "free": tot("free"), "held": tot("held"),
                  "sheds": tot("sheds"),
                  "draining": sum(1 for r in rows if r.get("draining")),
                  "lease_churn": tot("acq") + tot("lost")}
        return {"replica": self.replica_id, "lease_ttl_s": self.lease_ttl_s,
                "heartbeat_s": self.heartbeat_s, "totals": totals,
                "replicas": rows, "ts": round(time.time(), 3)}

    def shed_view(self) -> dict:
        """Compact cluster context for 429 bodies — the same cached
        peer data the Retry-After hint consults, so a shed client can
        see WHY the hint says what it says (peers with free capacity =
        the steal path will likely pick the job up)."""
        try:
            peers = self.peers(max_age_s=max(self.heartbeat_s, 1.0))
        except Exception:
            peers = []
        return {"replica": self.replica_id,
                "replicas": 1 + len(peers),
                "peer_free": sum(max(0, int(p.get("free", 0) or 0))
                                 for p in peers if p.get("steal")),
                "peer_queued": sum(max(0, int(p.get("queued", 0) or 0))
                                   for p in peers)}

    def peer_free_total(self) -> int:
        """Cluster-wide advertised free capacity — the Retry-After
        estimator's steal-path signal (0 on any store hiccup: fail
        toward the conservative local estimate).  Served from the
        heartbeat-cadence peer cache: a shed storm must not become a
        KEYS storm."""
        try:
            return sum(max(0, int(p.get("free", 0) or 0))
                       for p in self.peers(
                           max_age_s=max(self.heartbeat_s, 1.0))
                       if p.get("steal"))
        except Exception:
            return 0

    def steal_once(self) -> int:
        """One steal scan: when this replica is idle, claim queued jobs
        from the most loaded peer's admission namespace, up to our idle
        capacity.  Returns how many were stolen."""
        m = self._miner
        if m is None or not self.steal_enabled or self._quiesced:
            return 0
        budget = m.idle_capacity()
        if budget <= 0 or m.queue_size() > 0:
            return 0
        try:
            peers = self.peers()
        except Exception:
            return 0
        stolen = 0
        for p in sorted(peers,
                        key=lambda q: -int(q.get("queued", 0) or 0)):
            if stolen >= budget or int(p.get("queued", 0) or 0) <= 0:
                continue
            prefix = f"fsm:admission:{p.get('replica', '')}:"
            try:
                # cursor scan, early-terminated at the budget: the walk
                # reads at most one extra batch past what it can claim.
                # The scan's wire round-trips happen lazily INSIDE this
                # loop, so the whole iteration sits in the try — a
                # store hiccup walking one peer's namespace moves on to
                # the next peer instead of aborting the pass
                for key in self._store.scan_iter(prefix, count=64):
                    if stolen >= budget:
                        break
                    uid = key[len(prefix):]
                    try:
                        if self._steal_one(key, uid,
                                           p.get("replica", "")):
                            stolen += 1
                    except Exception as exc:
                        _STEAL_TOTAL.inc(outcome="error")
                        log_event("job_steal_failed", uid=uid,
                                  error=str(exc))
            except Exception as exc:
                log_event("job_steal_scan_failed",
                          victim=p.get("replica", ""), error=str(exc))
                continue
        return stolen

    def _steal_one(self, marker_key: str, uid: str, victim: str) -> bool:
        """The two-phase claim.  Phase 1: win the marker DEL (exclusive
        against the victim's dequeue AND other thieves).  Phase 2: take
        the lease over with a fresh, larger fencing token and resubmit
        the journaled request through our own admission path.  A failure
        after phase 1 releases the lease and leaves a journal orphan the
        periodic recovery pass re-adopts — loud, slow, never lost."""
        from spark_fsm_tpu.service.model import ServiceRequest

        faults.fault_site("lease.steal", uid=uid, victim=victim)
        if self._store.delete(marker_key) < 1:
            _STEAL_TOTAL.inc(outcome="lost_race")
            return False
        raw = self._store.peek(f"fsm:journal:{uid}")
        entry = self._parse(raw)
        if not entry.get("request"):
            _STEAL_TOTAL.inc(outcome="lost_race")  # settled under us
            return False
        t0 = self._clock()
        token = int(self._store.incr(_TOKEN_KEY))
        # unconditional overwrite: the victim's queued-job lease is live,
        # but the marker DEL above already guarantees it will DROP the
        # job at dequeue — and our larger token fences any interleaving
        self._store.set_px(self._lease_key(uid), self._payload(token),
                           self._ttl_ms)
        self._set_held(uid, token, t0 + self.lease_ttl_s)
        # a steal IS an adoption: stage the bumped counter so the
        # resubmit's journal intent carries it — the crash-loop
        # quarantine budget ([cluster] max_adoptions) counts holders
        # lost to steals and crashes alike
        bump = getattr(self._miner, "note_adoption", None)
        if bump is not None:
            try:
                n = int(entry.get("adoptions") or 0)
            except (TypeError, ValueError):
                n = 0
            bump(uid, n + 1)
        req = ServiceRequest("fsm", "train", {
            str(k): str(v) for k, v in entry["request"].items()})
        try:
            self._miner.submit(req)
        except Exception as exc:
            # we could not admit it after all (filled up between the
            # idle check and here, uid conflict, store hiccup): UNDO the
            # claim so nothing is lost — restore the victim's journal
            # intent verbatim and its admission marker, then release our
            # lease.  If the victim's worker has not reached the uid
            # yet, it wins the restored marker at dequeue and simply
            # runs the job (the heartbeat's journal-gated NX reacquire
            # re-owns the lease seamlessly); if it already dropped it,
            # marker+journal form an orphan the next steal scan or
            # recovery pass picks up.  Either way: slower, never lost.
            try:
                self._store.set(f"fsm:journal:{uid}", raw)
                self._store.set(marker_key, "1")
            except Exception as restore_exc:
                log_event("job_steal_restore_failed", uid=uid,
                          error=str(restore_exc))
            # the staged adoption counter must not leak onto an
            # unrelated future admit of the same uid
            getattr(self._miner, "_adoptions_pending", {}).pop(uid, None)
            self.release(uid)
            _STEAL_TOTAL.inc(outcome="error")
            log_event("job_steal_resubmit_failed", uid=uid, victim=victim,
                      error=str(exc))
            return False
        _STEAL_TOTAL.inc(outcome="stolen")
        # steal latency: victim's admission (journal intent ts) to this
        # successful claim + resubmit — the histogram the ROADMAP's
        # "jobs/sec at fixed p99" story reads load-balancing lag from
        try:
            ts0 = float(entry.get("ts") or 0)
            if ts0 > 0:
                obsplane.observe_steal_latency(time.time() - ts0)
        except (TypeError, ValueError):
            pass
        log_event("job_stolen", uid=uid, victim=victim,
                  replica=self.replica_id)
        obs.lifecycle(uid, "stolen", side="thief", victim=victim,
                      replica=self.replica_id)
        obs.flush_trace(uid)
        return True

    # ---------------------------------------------------------- lifecycle

    def start(self, miner, recover: Optional[Callable[[], object]] = None
              ) -> None:
        """Wire the manager to its Miner and start the heartbeat thread
        (``heartbeat_s`` <= 0 means manual ticks — tests drive
        :meth:`tick` directly for determinism)."""
        self._miner = miner
        self._recover = recover
        if self.heartbeat_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fsm-lease-{self.replica_id[:8]}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            self.tick()

    def tick(self) -> None:
        """One heartbeat: publish load, renew held leases, and (on
        cadence) steal + recover.  Each phase is isolated — a store
        hiccup in one must not starve the others, and the thread must
        never die."""
        if self._guard is not None:
            # outage guard first: a healed store replays the spool (and
            # un-stalls jobs) BEFORE renewals re-prove the leases the
            # replay just reacquired
            try:
                self._guard.tick()
            except Exception as exc:
                log_event("storeguard_tick_failed", error=str(exc))
        try:
            self.publish_heartbeat()
        except Exception as exc:
            log_event("lease_heartbeat_failed", error=str(exc))
        try:
            self.renew_all()
        except Exception as exc:
            log_event("lease_renew_pass_failed", error=str(exc))
        try:
            self.steal_once()
        except Exception as exc:
            log_event("lease_steal_pass_failed", error=str(exc))
        if self._recover is not None and not self._quiesced:
            now = self._clock()
            if now >= self._next_recover:
                self._next_recover = now + self.recover_every_s
                try:
                    self._recover()
                except Exception as exc:
                    log_event("lease_periodic_recovery_failed",
                              error=str(exc))
        # background integrity scrub (ISSUE 18) rides the heartbeat
        # cadence in clustered boots — next-due gating lives inside the
        # scrubber, this is one cheap global read per tick when idle
        try:
            from spark_fsm_tpu.service import integrity
            integrity.tick()
        except Exception as exc:
            log_event("integrity_scrub_failed", error=str(exc))
        # usage-ledger flush (ISSUE 19) rides the same cadence: settled
        # job vectors and avoided-cost credits land in the durable
        # fsm:usage:{tenant} records through the fenced write path —
        # min-interval gating lives inside the meter, one global read
        # per tick when idle or disabled
        try:
            from spark_fsm_tpu.service import usage
            usage.tick()
        except Exception as exc:
            log_event("usage_flush_failed", error=str(exc))
        # degraded-topology gossip + probe (ISSUE 20) rides the same
        # cadence: adopt peers' advertised mesh views (monotone merge —
        # max epoch, union dead rows) and run the cadenced zero-width
        # row probe.  One module-global read per tick when the guard is
        # off; probe cadence gating lives inside the guard.
        try:
            from spark_fsm_tpu.service import meshguard
            g = meshguard.get()
            if g is not None:
                for p in self.peers(max_age_s=self.heartbeat_s or None):
                    g.merge_peer(p.get("mesh"))
                g.maybe_probe()
        except Exception as exc:
            log_event("meshguard_tick_failed", error=str(exc))

    def quiesce(self) -> None:
        """Stop pulling NEW work (steal scans, periodic adoption) while
        renewals continue — called at the START of the shutdown drain.
        Without it, a draining replica could steal a healthy peer's
        queued job only to give it a durable 'service shutting down'
        failure the client never deserved."""
        self._quiesced = True

    @property
    def draining(self) -> bool:
        return self._draining

    def set_draining(self, flag: bool = True) -> None:
        """Flip the scale-down drain state (Miner.drain): the heartbeat
        advertises ``draining`` with zero free capacity and the steal/
        adoption pulls stop — a departing replica must shed load, not
        attract it.  Publishes a fresh heartbeat immediately (best
        effort) so peers see the transition within one round-trip, not
        one heartbeat period."""
        self._draining = bool(flag)
        if flag:
            self._quiesced = True
        try:
            self.publish_heartbeat()
        except Exception as exc:
            log_event("lease_drain_heartbeat_failed", error=str(exc))

    def peer_inflight_fp(self, fp: str) -> bool:
        """Is ``fp`` (a dataset fingerprint) currently in flight as a
        coalescing leader on some peer?  Served from the heartbeat-
        cadence peer cache (the submit hot path must not scan the
        store); False on any error — the hint only ever costs a
        duplicate mine, never correctness."""
        try:
            for p in self.peers(max_age_s=max(self.heartbeat_s, 1.0)):
                if fp in (p.get("fps") or ()):
                    return True
        except Exception:
            pass
        return False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(max(2.0, 2 * self.heartbeat_s))
            self._thread = None
        try:  # retract the heartbeat record so peers stop seeing us
            self._store.delete(self._hb_key)
        except Exception:
            pass

    def stats(self) -> dict:
        """The /admin/stats ``cluster`` block.  Peers come from the
        heartbeat-cadence cache — a stats poller must not drive KEYS
        scans against the shared store."""
        try:
            n_peers = len(self.peers(
                max_age_s=max(self.heartbeat_s, 1.0)))
        except Exception:
            n_peers = None
        return {"replica": self.replica_id,
                "lease_ttl_s": self.lease_ttl_s,
                "heartbeat_s": self.heartbeat_s,
                "steal": self.steal_enabled,
                "held": len(self._held),
                "peers": n_peers}
