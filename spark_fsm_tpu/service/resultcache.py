"""Result-reuse tier above admission (ISSUE 12) — content-addressed
dataset fingerprints, in-flight request coalescing, and dominance-based
cache serving.

At millions of users most mine requests are redundant: same dataset,
identical or strictly weaker parameters.  This module sits between HTTP
admission and the Miner mailbox (service/actors.Miner.submit) and keeps
redundant work off the device entirely, in three cooperating layers:

- **Content-addressed fingerprints**: every resolved dataset gets a
  canonical streaming hash (data/spmf.fingerprint_db), computed once at
  dataset load and stamped on the job's JobControl — so two requests
  naming the same data resolve to one cache key regardless of how they
  spelled the source.  INLINE payloads hash at admission (the request
  carries the content); SYNTH specs are deterministic generators whose
  spec→fingerprint mapping is learned at first load
  (``fsm:rescache-src:{srckey}``); FILE paths resolve through the SAME
  learned mapping gated on an immutability validator (mtime + size +
  content sample, data/spmf.file_validator — ISSUE 13 / ROADMAP 2b):
  an untouched artifact fp-resolves at admission and unlocks dominance
  serving for the FILE spelling, any mismatch falls back to the
  mutable path; truly mutable sources (TRACKED/JDBC/ELASTIC/PIWIK)
  never resolve a fingerprint at admission — their content can change
  under the same spelling, so they only coalesce (identical in-flight
  spec) and populate entries for OTHER spellings (an INLINE request
  for the same bytes still hits).  In CLUSTER mode each replica's
  heartbeat piggybacks its in-flight leaders' fingerprints; a local
  miss whose fp is in flight on a peer sheds with a ~2-heartbeat
  Retry-After instead of admitting a duplicate cold mine (ROADMAP 2c
  — a hint: replica-local coalescing semantics are unchanged).

- **In-flight coalescing**: an identical request (same dataset
  identity, algorithm, and effective result-affecting parameters —
  plugins.effective_params) arriving while a matching job is queued or
  running attaches as a *follower* instead of admitting.  One
  execution; fan-out delivery at the leader's sink.  Each follower
  still gets its own journal intent, lease, job-control entry, trace
  lifecycle, and result-store records, so crash recovery
  (service/actors.recover_orphans) and /admin/trace behave exactly as
  for a solo job — a kill -9 of the process leaves follower journal
  entries that the boot recovery pass settles, never a stuck uid.  In
  cluster mode followers attach only to leaders whose lease THIS
  replica holds; otherwise they admit normally (correct, just colder).
  A leader that reaches any terminal state other than success (cancel,
  deadline, failure, drain, steal, fence) has its followers
  re-dispatched through the normal admission path as independent cold
  mines — a leader's abort is its client's decision, not the
  followers'.

- **Dominance serving**: a completed cached entry
  (``fsm:rescache:{fingerprint}:{algo}``) serves any *dominated*
  request by filtering the cached result set on the host — zero device
  work.  The per-algorithm predicates are deliberately conservative and
  proven in docs/DESIGN.md ("Dominance predicates"):

    SPADE/SPADE_TPU (patterns): same fingerprint + EXACTLY equal
      maxgap/maxwindow + higher-or-equal absolute minsup.  Supports are
      invariant under a pure minsup raise, so filtering by
      ``sup >= minsup'`` is byte-exact.  Stricter constraints are NOT
      served (supports change under a tighter gap/window — recounting
      would need the data).
    TSR/TSR_TPU (rules, tie-inclusive top-k): smaller-or-equal k,
      same-or-higher minconf, same-or-stricter max_side — accepted only
      when the re-derived tie-inclusive threshold over the filtered
      cached set is >= the cached run's own threshold s_k0 (or the
      cached run was exhaustive, i.e. found < k rules).  Rules the
      cached run pruned all have sup < s_k0, so none can enter the
      filtered top-k; when the check fails the request MISSES and mines
      cold.

Cache entries live in the existing ResultStore with LRU byte-budget
eviction over a cursor SCAN; in cluster mode the entry write is fenced
through the PR 8 lease path (the writer proves it still owns the
producing job).  EVERY lookup/serve/coalesce path degrades to a plain
cold mine on any error — the tier can lose reuse, never correctness.
Disabled (``[rescache] enabled = false``, the default) the Miner holds
no cache instance and submit pays one attribute read; bench_smoke's
dispatch-shape counters stay byte-identical.

Fault sites: ``rescache.lookup`` / ``rescache.store`` (utils/faults
KNOWN_SITES), swept by tests/test_chaos.py.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_fsm_tpu import config
from spark_fsm_tpu.service import integrity, model, obsplane, usage
from spark_fsm_tpu.service.model import ServiceRequest, Status
from spark_fsm_tpu.utils import envelope, faults, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event

# ---------------------------------------------------------------- metrics
# The fsm_rescache_* vocabulary: unlabelled counters auto-seed at 0; the
# one labelled family seeds its op vocabulary so a fresh scrape shows
# every error class at 0 instead of no-data (the PR 9/10 hygiene
# pattern; scripts/obs_smoke.py pins all of these as non-orphans).

_HITS = obs.REGISTRY.counter(
    "fsm_rescache_hits_total",
    "requests served verbatim from a completed cache entry (exact "
    "effective-parameter match; zero device work)")
_DOMINATED = obs.REGISTRY.counter(
    "fsm_rescache_dominated_serves_total",
    "dominated requests served by host-side filtering of a cached "
    "result set (strictly weaker parameters; zero device work)")
_MISSES = obs.REGISTRY.counter(
    "fsm_rescache_misses_total",
    "reuse lookups that found nothing servable — the request mined cold")
_COALESCED = obs.REGISTRY.counter(
    "fsm_rescache_coalesced_total",
    "requests attached as followers of an identical in-flight job "
    "(one execution, fan-out delivery)")
_EVICTIONS = obs.REGISTRY.counter(
    "fsm_rescache_evictions_total",
    "cache entries evicted by the LRU byte budget")
_BYTES_TOTAL = obs.REGISTRY.counter(
    "fsm_rescache_bytes_total",
    "lifetime bytes written into cache entries")
_BYTES = obs.REGISTRY.gauge(
    "fsm_rescache_bytes",
    "resident cache-entry bytes (recomputed at each store/evict pass)")
_BYTES.set(0)  # gauges don't auto-seed; a fresh scrape must show 0
_ERRORS = (obs.REGISTRY.counter(
    "fsm_rescache_errors_total",
    "result-reuse operations that failed and degraded to a cold mine, "
    "by op — the tier loses reuse on error, never correctness")
    .seed(op="lookup").seed(op="store").seed(op="serve")
    .seed(op="coalesce").seed(op="fanout"))


# request params that do NOT affect mined results: excluded from the
# source identity (everything else in req.data names the data source)
_NON_SOURCE_PARAMS = frozenset({
    "uid", "algorithm", "support", "k", "minconf", "max_side",
    "maxgap", "maxwindow", "priority", "deadline_s", "retries",
    "checkpoint", "checkpoint_every_s", "profile", "use_pallas",
    "resident", "incremental",
})

# sources whose content can change under the same request spelling —
# never fingerprint-resolvable at admission (see module docstring).
# FILE left this set in ISSUE 13 (ROADMAP 2b): an mtime+size+content-
# sample validator (data/spmf.file_validator) now witnesses that a
# path still names the bytes it named at the last load, so IMMUTABLE
# file artifacts fp-resolve at admission and unlock dominance serving;
# any validator mismatch falls back to this mutable (coalesce-only)
# path.
_MUTABLE_SOURCES = frozenset(
    {"TRACKED", "JDBC", "ELASTIC", "PIWIK"})

_PEER_HINTS = obs.REGISTRY.counter(
    "fsm_rescache_peer_hints_total",
    "submits shed with a peer-aware Retry-After because an identical "
    "dataset fingerprint was in flight on a peer replica (ROADMAP 2c: "
    "the cross-replica coalesce hint — the retry hits the cache entry "
    "the peer publishes)")


def entry_key(fp: str, algo: str) -> str:
    return f"fsm:rescache:{fp}:{algo}"


def _lru_key(fp: str, algo: str) -> str:
    return f"fsm:rescache-lru:{fp}:{algo}"


def _src_key(srckey: str) -> str:
    return f"fsm:rescache-src:{srckey}"


def sidecar_key_for(ekey: str) -> str:
    """``fsm:rescache:{fp}:{algo}`` -> its LRU sidecar key."""
    return "fsm:rescache-lru:" + ekey[len("fsm:rescache:"):]


def entry_key_for_sidecar(skey: str) -> str:
    return "fsm:rescache:" + skey[len("fsm:rescache-lru:"):]


def parse_entry(payload: Optional[str],
                check_digest: bool = True) -> Optional[dict]:
    """Decode one cache-entry payload; with ``check_digest`` also
    cross-check the stored ``rules_digest`` against a recompute over the
    payload string — the PR 17 artifact cache keys compiled tries on
    that digest, so an artifact must never be built from bytes the
    digest does not vouch for.  None = undecodable or digest mismatch
    (the caller treats it as corrupt).  Entries predating the digest
    field pass undigested."""
    if payload is None:
        return None
    try:
        ent = json.loads(payload)
    except ValueError:
        return None
    if not isinstance(ent, dict) or not isinstance(ent.get("payload"), str):
        return None
    if check_digest and ent.get("digest"):
        from spark_fsm_tpu.ops.rule_trie import rules_digest

        if rules_digest(ent["payload"]) != ent["digest"]:
            return None
    return ent


def open_entry(store, fp: str, algo: str, check_digest: bool = False):
    """Verified read of one cache entry: envelope unwrap + decode
    (+ digest cross-check when asked — the artifact-build path).
    Returns ``(ent, payload_size)``, or None — and on CORRUPT bytes
    first quarantines the entry and drops its sidecar, so the caller's
    fall-through to a cold mine also heals the keyspace: corrupt bytes
    are never served, and never crash admission (ISSUE 18)."""
    key = entry_key(fp, algo)
    raw = store.get(key)
    if raw is None:
        return None
    payload, verdict = envelope.unwrap(raw)
    ent = None
    if verdict != "corrupt":
        ent = parse_entry(payload, check_digest=check_digest)
        if ent is None:
            verdict = "corrupt"
    integrity.note_read("rescache", verdict)
    if ent is not None:
        return ent, len(payload)
    integrity.quarantine(store, key, raw, "rescache", move=True)
    store.delete(sidecar_key_for(key))
    log_event("rescache_entry_quarantined", key=key)
    return None


def write_sidecar(store, ekey: str, ent: dict, size: int,
                  ts: Optional[float] = None) -> None:
    """(Re)write an entry's LRU sidecar — shared by the store path, the
    serve-time LRU touch, and the scrubber's sidecar repair (which
    passes no ``ts`` so the re-derived sidecar keeps the ENTRY's age
    instead of artificially refreshing its eviction rank)."""
    if ts is None:
        try:
            ts = float(ent.get("ts") or time.time())
        except (TypeError, ValueError):
            ts = time.time()
    store.set(sidecar_key_for(ekey), envelope.wrap(json.dumps(
        {"ts": ts, "bytes": size, "digest": ent.get("digest")})))


def _conf_frac(minconf: float) -> Tuple[int, int]:
    """minconf as an exact (num, den) — the SAME spelling models/tsr
    uses (Fraction over str), so serve-side confidence tests agree with
    the engines bit-for-bit."""
    from fractions import Fraction

    f = Fraction(str(minconf))
    return f.numerator, f.denominator


class _Identity:
    """A request's reuse identity: source key (hash of the source
    spelling), optional content fingerprint, the normalized
    result-affecting params (plugins.effective_params), and — for FILE
    spellings — the immutability validator that gates the learned
    path→fingerprint mapping."""

    __slots__ = ("source", "srckey", "stable", "fp", "params",
                 "validator")

    def __init__(self, source: str, srckey: str, stable: bool,
                 fp: Optional[str], params: dict,
                 validator: Optional[dict] = None):
        self.source = source
        self.srckey = srckey
        self.stable = stable
        self.fp = fp
        self.params = params
        self.validator = validator


class _Follower:
    __slots__ = ("uid", "req", "ctl", "priority", "t0")

    def __init__(self, uid: str, req: ServiceRequest,
                 ctl: jobctl.JobControl, priority: str):
        self.uid = uid
        self.req = req
        self.ctl = ctl
        self.priority = priority
        self.t0 = time.monotonic()


def build_for(miner) -> Optional["ResultCache"]:
    """The Miner's constructor hook: a cache instance when the boot
    config enables the tier, else None (one attribute read per submit
    thereafter — the disabled-cost pin)."""
    if not config.get_config().rescache.enabled:
        return None
    return ResultCache(miner)


class ResultCache:
    """One per Miner: the coalescing registry is process-local (a
    follower's fan-out must come from the worker that runs its leader),
    the completed-entry cache lives in the shared ResultStore."""

    def __init__(self, miner) -> None:
        self.miner = miner
        self.store = miner.store
        self.mgr = miner._lease
        rcfg = config.get_config().rescache
        self.max_bytes = int(rcfg.max_bytes)
        self.coalesce_enabled = bool(rcfg.coalesce)
        self.dominance_enabled = bool(rcfg.dominance)
        self._lock = threading.Lock()
        # serializes follower ATTACH I/O (journal/lease/status writes)
        # among attachers only — the registry lock above must stay
        # store-I/O-free because leader_admitted (inside the Miner's
        # enqueue section) and every fan-out pop take it
        self._attach_lock = threading.Lock()
        # coalescing registry: ckey -> leader uid; leader uid -> state
        self._leaders: Dict[str, str] = {}
        self._by_leader: Dict[str, dict] = {}
        # uids intercepted as prospective leaders, awaiting the admit
        # outcome (promoted just before enqueue, dropped on any abort)
        self._pending: Dict[str, str] = {}
        # prospective leaders' resolved dataset fingerprints — becomes
        # the heartbeat's in-flight hint (ROADMAP 2c) once promoted
        self._pending_fp: Dict[str, str] = {}
        # FILE requests' ADMISSION-time validators, keyed by uid: the
        # learned path→fp mapping is stored only when the load-time
        # validator equals this one, proving the file did not change
        # between admission and the load whose parse produced the
        # fingerprint (without the check, a rewrite racing a slow load
        # would bind the OLD content's fp to the NEW file's validator
        # and serve stale results).  Size-capped: a dropped entry only
        # loses one job's reuse, never correctness.
        self._admit_validator: Dict[str, dict] = {}

    # ------------------------------------------------------------ identity

    def _identity(self, req: ServiceRequest) -> _Identity:
        """Resolve the request's reuse identity.  Raises ValueError on
        malformed params — the caller degrades to the cold path, where
        the same ValueError surfaces through normal admission."""
        from spark_fsm_tpu.service import plugins

        params = plugins.effective_params(req)
        source = (req.param("source") or "FILE").upper()
        fp = None
        if source == "INLINE":
            # the request IS the content: hash it at admission (cost is
            # one parse of the payload the worker would parse anyway)
            from spark_fsm_tpu.data.spmf import fingerprint_db, parse_spmf

            text = req.param("sequences")
            if text is None:
                raise ValueError("INLINE source needs 'sequences'")
            fp = fingerprint_db(parse_spmf(text))
            spec: Dict[str, str] = {"source": source}
        elif source == "SYNTH":
            spec = {"source": source,
                    "dataset": req.param("dataset", "bms_webview1"),
                    "scale": repr(float(req.param("scale", "0.01")))}
        elif source == "FILE":
            # FILE artifacts (ROADMAP 2b): the path names the content
            # only while the immutability validator holds — computed
            # here (one stat + a bounded head/tail sample read, far
            # cheaper than the parse the worker pays anyway) and
            # compared against the learned mapping in _resolve_fp.
            # None (unreadable path) degrades to the mutable path; the
            # cold mine surfaces the real error.
            from spark_fsm_tpu.data.spmf import file_validator

            path = req.param("path") or ""
            spec = {"source": source, "path": path}
            validator = file_validator(path) if path else None
            srckey = hashlib.sha256(
                json.dumps(spec, sort_keys=True).encode()).hexdigest()
            return _Identity(source, srckey, False, None, params,
                             validator=validator)
        else:
            # every non-control param is source-naming (path, db, url,
            # query, topic, ... and for custom sources even an inline
            # payload): the spec hash must cover all of them, or two
            # requests for DIFFERENT data could coalesce
            spec = {"source": source}
            for k in sorted(req.data):
                if k not in _NON_SOURCE_PARAMS:
                    spec[k] = str(req.data[k])
        srckey = hashlib.sha256(
            json.dumps(spec, sort_keys=True).encode()).hexdigest()
        stable = source in ("INLINE", "SYNTH")
        return _Identity(source, srckey, stable, fp, params)

    def _resolve_fp(self, ident: _Identity) -> Optional[str]:
        """Admission-time fingerprint: direct for INLINE, learned map
        for SYNTH, validator-gated learned map for FILE (the mapping
        is trusted only while the immutability witness still matches
        the one recorded at load — a touched/rewritten file misses and
        mines cold), None for mutable sources (their spelling does not
        pin their content)."""
        if ident.fp is not None:
            return ident.fp
        if not ident.stable and ident.validator is None:
            return None
        raw = self.store.peek(_src_key(ident.srckey))
        if not raw:
            return None
        try:
            ent = json.loads(raw)
        except ValueError:
            return None
        if not isinstance(ent, dict):
            return None
        if ident.stable:
            return ent.get("fp") or None
        # FILE: the learned fingerprint holds only under an EXACT
        # validator match (mtime_ns + size + content sample)
        if ent.get("validator") == ident.validator:
            return ent.get("fp") or None
        return None

    def _ckey(self, fp: Optional[str], ident: _Identity) -> str:
        """Coalescing identity: dataset (fingerprint when resolvable,
        source key otherwise) + algorithm + result-affecting params.
        minsup_abs is derived, not part of the spelling — identical
        requests share the raw support value."""
        p = dict(ident.params)
        p.pop("minsup_abs", None)
        return json.dumps([fp or ("src:" + ident.srckey), p],
                          sort_keys=True)

    # ----------------------------------------------------------- admission

    def intercept(self, req: ServiceRequest, priority: str,
                  deadline_s: Optional[float]) -> Optional[str]:
        """The admission hook: "served" (request answered from a
        completed entry), "coalesced" (attached as a follower), or None
        (proceed with normal cold admission — possibly registered as a
        prospective leader).  NEVER raises: any error counts and falls
        through to the cold path."""
        try:
            faults.fault_site("rescache.lookup", uid=req.uid)
            ident = self._identity(req)
        except Exception:
            # malformed params / injected lookup fault: the cold path
            # owns the error surface (a bad request still fails there)
            _ERRORS.inc(op="lookup")
            return None
        try:
            fp = self._resolve_fp(ident)
            if fp is not None and self.dominance_enabled:
                out = self._try_serve(req, fp, ident, priority)
                if out is not None:
                    return out
            if self.coalesce_enabled:
                ckey = self._ckey(fp, ident)
                if self._try_follow(req, ckey, priority, deadline_s):
                    return "coalesced"
                if (fp is not None and self.mgr is not None
                        and self.mgr.peer_inflight_fp(fp)):
                    # cross-replica coalesce HINT (ROADMAP 2c): the
                    # fingerprint is in flight on a peer — tell the
                    # submit layer to shed with a ~2-heartbeat
                    # Retry-After instead of admitting a duplicate
                    # cold mine.  Hint only: nothing here attaches
                    # across replicas, and the retry either hits the
                    # entry the peer published or mines cold.
                    _PEER_HINTS.inc()
                    log_event("rescache_peer_hint", uid=req.uid,
                              fp=fp[:16])
                    return "peer-inflight"
                with self._lock:
                    self._pending[req.uid] = ckey
                    if fp is not None:
                        self._pending_fp[req.uid] = fp
            if ident.validator is not None:
                with self._lock:
                    self._admit_validator[req.uid] = ident.validator
                    while len(self._admit_validator) > 1024:
                        self._admit_validator.pop(
                            next(iter(self._admit_validator)))
            _MISSES.inc()
            return None
        except Exception as exc:
            _ERRORS.inc(op="lookup")
            log_event("rescache_error", op="lookup", uid=req.uid,
                      error=str(exc))
            with self._lock:
                self._pending.pop(req.uid, None)
                self._pending_fp.pop(req.uid, None)
            return None

    def leader_admitted(self, uid: str) -> None:
        """Promote a pending interception to a live leader — called
        under the Miner's enqueue decision, strictly BEFORE the request
        reaches the queue, so a follower can never attach to a uid that
        already settled."""
        with self._lock:
            ckey = self._pending.pop(uid, None)
            fp = self._pending_fp.pop(uid, None)
            if ckey is None or ckey in self._leaders:
                return  # two same-key admits raced: first one leads
            self._leaders[ckey] = uid
            self._by_leader[uid] = {"ckey": ckey, "followers": [],
                                    "fp": fp}

    def admit_aborted(self, uid: str) -> None:
        """Drop a prospective leader whose admission never enqueued
        (shed, conflict, journal failure, shutdown)."""
        with self._lock:
            self._pending.pop(uid, None)
            self._pending_fp.pop(uid, None)
            self._admit_validator.pop(uid, None)

    def inflight_fps(self) -> List[str]:
        """Dataset fingerprints of live coalescing leaders — the
        heartbeat snapshot's cross-replica hint payload (bounded by
        the caller; a leader whose fp is still unknown contributes
        nothing)."""
        with self._lock:
            return sorted({s["fp"] for s in self._by_leader.values()
                           if s.get("fp")})

    # ---------------------------------------------------------- coalescing

    def _try_follow(self, req: ServiceRequest, ckey: str, priority: str,
                    deadline_s: Optional[float]) -> bool:
        with self._lock:
            leader = self._leaders.get(ckey)
            if leader is None:
                return False
            # the leader must still be live here: a registered control
            # entry proves it is queued or running on THIS miner; in
            # cluster mode the lease must be ours too (a stolen/
            # adopted leader fans out elsewhere)
            if jobctl.get(leader) is None:
                return False
            if self.mgr is not None \
                    and self.mgr.token_of(leader) is None:
                return False
        fresh_lease = journaled = False
        ctl = None
        attached = False
        try:
            with self._attach_lock:
                # liveness check + journal intent are atomic AMONG
                # ATTACHERS: two racing submits of the same uid
                # serialize here and the loser sees the fresh intent
                # (falling through to the cold path's 409); the
                # registry lock stays out of this store I/O
                entry = self.store.journal_get(req.uid)
                if entry is not None:
                    try:
                        if (json.loads(entry).get("incarnation")
                                == self.miner.incarnation):
                            return False
                    except ValueError:
                        pass
                if self.mgr is not None:
                    # own lease per follower: fan-out writes ride the
                    # fenced path exactly like a solo job's sink
                    fresh_lease = self.mgr.token_of(req.uid) is None
                    self.mgr.acquire(req.uid)  # LeaseHeld -> except
                self.store.clear_job(req.uid)
                self.store.journal_set(req.uid, json.dumps({
                    "uid": req.uid,
                    "incarnation": self.miner.incarnation,
                    "replica": (self.mgr.replica_id
                                if self.mgr is not None else None),
                    "ts": round(time.time(), 3),
                    "checkpoint": False,
                    "priority": priority,
                    "coalesced_into": leader,
                    "request": dict(req.data),
                }))
                journaled = True
                self.store.add_status(req.uid, Status.STARTED)
                self.store.incr("fsm:metric:jobs_submitted")
                ctl = jobctl.register(req.uid, deadline_s,
                                      priority=priority)
                ctl.follower_of = leader
                if self.mgr is not None:
                    self.mgr.attach(req.uid, ctl)
            with self._lock:
                # the leader may have settled (or lost its ckey to a
                # successor) during the attach I/O: only a leader still
                # registered can be trusted to fan out — otherwise roll
                # back and mine cold
                if self._leaders.get(ckey) == leader \
                        and jobctl.get(leader) is not None:
                    self._by_leader[leader]["followers"].append(
                        _Follower(req.uid, req, ctl, priority))
                    attached = True
        except Exception as exc:
            _ERRORS.inc(op="coalesce")
            log_event("rescache_error", op="coalesce", uid=req.uid,
                      error=str(exc))
        if not attached:
            # unwind the partial attach: a surviving live-looking
            # journal entry would 409 every future resubmit of the uid
            try:
                if journaled:
                    self.store.journal_clear(req.uid)
            except Exception:
                pass
            if ctl is not None:
                jobctl.release_entry(ctl)
            if self.mgr is not None and fresh_lease:
                try:
                    self.mgr.release(req.uid)
                except Exception:
                    pass
            return False
        _COALESCED.inc()
        log_event("job_coalesced", uid=req.uid, leader=leader,
                  priority=priority)
        obs.trace_begin(req.uid,
                        algorithm=req.param("algorithm", "SPADE_TPU"),
                        source=req.param("source", "FILE"))
        obs.lifecycle(req.uid, "admitted", priority=priority,
                      coalesced_into=leader,
                      replica=(self.mgr.replica_id
                               if self.mgr is not None else None))
        obs.flush_trace(req.uid)
        return True

    def _pop_followers(self, uid: str) -> List[_Follower]:
        with self._lock:
            state = self._by_leader.pop(uid, None)
            if state is None:
                return []
            if self._leaders.get(state["ckey"]) == uid:
                del self._leaders[state["ckey"]]
            return state["followers"]

    # ------------------------------------------------------ dataset stamps

    def note_dataset(self, req: ServiceRequest, db,
                     ctl: Optional[jobctl.JobControl]) -> Optional[str]:
        """Worker-side fingerprint stamp, once per dataset load: compute
        the content hash, carry it on the JobControl, and learn the
        stable-source spec → fingerprint mapping.  Never raises — a
        failure here only loses reuse."""
        try:
            faults.fault_site("rescache.store", uid=req.uid)
            from spark_fsm_tpu.data.spmf import fingerprint_db

            fp = fingerprint_db(db)
            if ctl is not None:
                ctl.dataset_fp = fp
            ident = self._identity(req)
            learnable = ident.stable
            if ident.validator is not None:
                # FILE: the mapping may only bind this validator to
                # this fingerprint if the file provably did NOT change
                # between admission and now — the admission-time
                # validator must equal the one just recomputed.  A
                # rewrite racing the (possibly seconds-long) load
                # would otherwise pair the OLD content's fp with the
                # NEW file's validator and serve stale results; on any
                # mismatch (or an unknown admission validator) we skip
                # learning and the next untouched-run stores it.
                with self._lock:
                    v_admit = self._admit_validator.pop(req.uid, None)
                learnable = v_admit == ident.validator
            if ident.fp is None and learnable:
                # SYNTH: the deterministic generator spec now provably
                # names this content — admission can resolve it next
                # time.  FILE: witnessed-unchanged across the load.
                self.store.set(_src_key(ident.srckey), json.dumps(
                    {"fp": fp, "source": ident.source,
                     "validator": ident.validator}))
            # in-flight hint upkeep (ROADMAP 2c): a leader whose fp was
            # unknown at admission (first FILE mine of a path) becomes
            # visible to peers once the dataset is loaded
            with self._lock:
                state = self._by_leader.get(req.uid)
                if state is not None:
                    state["fp"] = fp
            return fp
        except Exception as exc:
            _ERRORS.inc(op="store")
            log_event("rescache_error", op="store", uid=req.uid,
                      error=str(exc))
            return None

    # ----------------------------------------------------- serving (reuse)

    def _try_serve(self, req: ServiceRequest, fp: str, ident: _Identity,
                   priority: str) -> Optional[str]:
        algo = ident.params["algo"]
        opened = open_entry(self.store, fp, algo)
        if opened is None:
            # missing — or corrupt: already quarantined, and the
            # request falls through to a cold mine (never served)
            return None
        ent, size = opened
        served = _servable(ent, ident.params)
        if served is None:
            return None
        payload, mode, n_results = served
        if not self._deliver(req, ent, payload, mode, n_results,
                             priority):
            return None
        (_HITS if mode == "exact" else _DOMINATED).inc()
        # LRU touch: serving refreshes the entry's eviction rank (the
        # sidecar also carries the entry's byte size so the eviction
        # sweep never has to read payloads)
        try:
            write_sidecar(self.store, entry_key(fp, algo), ent, size,
                          ts=time.time())
        except Exception:
            pass
        return "served"

    def _deliver(self, req: ServiceRequest, ent: dict, payload: str,
                 mode: str, n_results: int, priority: str) -> bool:
        """Synchronously settle ``req`` from the cache: the same
        durable shape as a solo job (journal intent → results →
        terminal status → journal clear), under the uid's own lease in
        cluster mode.  False = could not serve (live uid, lease held,
        store error) — the cold path takes over."""
        uid = req.uid
        t0 = time.monotonic()
        entry = self.store.journal_get(uid)
        if entry is not None:
            try:
                if (json.loads(entry).get("incarnation")
                        == self.miner.incarnation):
                    return False  # live uid: normal path 409s
            except ValueError:
                pass
        fresh_lease = False
        if self.mgr is not None:
            try:
                fresh_lease = self.mgr.token_of(uid) is None
                self.mgr.acquire(uid)
            except Exception:
                return False  # LeaseHeld/Unavailable: cold path decides
        journaled = False
        try:
            self.store.journal_set(uid, json.dumps({
                "uid": uid, "incarnation": self.miner.incarnation,
                "replica": (self.mgr.replica_id
                            if self.mgr is not None else None),
                "ts": round(time.time(), 3), "checkpoint": False,
                "priority": priority, "served_from_cache": mode,
                "request": dict(req.data)}))
            journaled = True
            self.store.clear_job(uid)
            self.store.add_status(uid, Status.STARTED)
            self.store.incr("fsm:metric:jobs_submitted")
            obs.trace_begin(uid,
                            algorithm=req.param("algorithm", "SPADE_TPU"),
                            source=req.param("source", "FILE"))
            obs.lifecycle(uid, "admitted", priority=priority,
                          served_from_cache=mode)
            stats = {"algorithm": ent["algo"],
                     "sequences": ent["n_sequences"],
                     "results": n_results,
                     "served_from_cache": mode,
                     "cache_uid": ent.get("uid"),
                     "dataset_s": 0.0, "mine_s": 0.0}
            self.store.set(f"fsm:stats:{uid}", json.dumps(stats))
            if ent["kind"] == "patterns":
                self.store.add_patterns(uid, payload)
            else:
                self.store.add_rules(uid, payload)
            self.store.add_status(uid, Status.TRAINED)
            self.store.add_status(uid, Status.FINISHED)
            self.store.journal_clear(uid)
            self.store.incr("fsm:metric:jobs_finished")
            e2e = time.monotonic() - t0
            obsplane.observe_job(priority, e2e, 0.0, e2e,
                                 tenant=(req.param("tenant")
                                         or obsplane.DEFAULT_TENANT))
            # avoided-cost credit (service/usage.py): this serve spent
            # ~zero device seconds where a cold mine would have spent
            # what the cached entry's recorded usage says it cost
            u = ent.get("usage") or {}
            usage.credit_avoided(
                req.param("tenant"),
                u.get("device_seconds_measured")
                or u.get("device_seconds_est") or 0.0, mode)
            obs.lifecycle(uid, "settled", outcome="finished",
                          served_from_cache=mode)
            obs.flush_trace(uid)
            if self.mgr is not None:
                self.mgr.release(uid)
            log_event("job_served_from_cache", uid=uid, mode=mode,
                      results=n_results, cache_uid=ent.get("uid"))
            return True
        except Exception as exc:
            _ERRORS.inc(op="serve")
            log_event("rescache_error", op="serve", uid=uid,
                      error=str(exc))
            # unwind so the cold path starts clean; best-effort — the
            # cold admission's clear_job re-wipes whatever remains.
            # Clear ONLY an intent WE wrote: when journal_set itself
            # failed, any surviving record is a predecessor's (e.g. a
            # dead replica's checkpointed orphan) and destroying it
            # would destroy its recoverability (same rule as _admit's
            # unwind in service/actors.py)
            try:
                if journaled:
                    self.store.journal_clear(uid)
            except Exception:
                pass
            if self.mgr is not None and fresh_lease:
                try:
                    self.mgr.release(uid)
                except Exception:
                    pass
            return False

    # ------------------------------------------------------ leader terminal

    def on_finished(self, req: ServiceRequest,
                    ctl: Optional[jobctl.JobControl], plugin, results,
                    stats: dict) -> None:
        """Leader success hook (called from the worker AFTER the sink,
        while the leader's lease is still held): store the cache entry,
        then fan the durable result out to every follower.  Never
        raises — the leader's job is already green."""
        uid = req.uid
        payload = None
        try:
            payload = (model.serialize_patterns(results)
                       if plugin.kind == "patterns"
                       else model.serialize_rules(results))
            self._store_entry(req, ctl, plugin, results, stats)
        except Exception as exc:
            _ERRORS.inc(op="store")
            log_event("rescache_error", op="store", uid=uid,
                      error=str(exc))
        for rec in self._pop_followers(uid):
            try:
                if payload is None:
                    raise RuntimeError("no fan-out payload")
                self._fanout_one(uid, rec, plugin.kind, payload, stats)
            except jobctl.JobAborted as exc:
                self._settle_follower_failure(rec, exc)
            except Exception as exc:
                _ERRORS.inc(op="fanout")
                log_event("rescache_error", op="fanout", uid=rec.uid,
                          leader=uid, error=str(exc))
                self._settle_follower_failure(rec, RuntimeError(
                    f"coalesced fan-out from leader {uid!r} failed: "
                    f"{exc}"))

    def _fanout_one(self, leader: str, rec: _Follower, kind: str,
                    payload: str, stats: dict) -> None:
        # the follower's OWN abort signals are owed first: a cancel or
        # deadline that landed while it waited must not be papered over
        jobctl.check_entry(rec.ctl)
        if self.mgr is not None:
            self.mgr.fence(rec.uid)  # raises JobLeaseLost when stale
        now = time.monotonic()
        if rec.ctl.started_t is None:
            rec.ctl.started_t = now
        self.store.clear_job(rec.uid, keep_status_log=True)
        self.store.set(f"fsm:stats:{rec.uid}", json.dumps(
            {**stats, "coalesced_into": leader}))
        if kind == "patterns":
            self.store.add_patterns(rec.uid, payload)
        else:
            self.store.add_rules(rec.uid, payload)
        self.store.add_status(rec.uid, Status.TRAINED)
        self.store.add_status(rec.uid, Status.FINISHED)
        self.store.journal_clear(rec.uid)
        jobctl.release_entry(rec.ctl)
        e2e = now - rec.ctl.submitted_t
        obsplane.observe_job(rec.priority, e2e, max(0.0, e2e), 0.0,
                             tenant=rec.ctl.tenant)
        # coalesced serve: the follower avoided the leader's measured
        # device cost (rode the same mine for free)
        u = stats.get("usage") or {}
        usage.credit_avoided(
            rec.ctl.tenant,
            u.get("device_seconds_measured")
            or u.get("device_seconds_est") or 0.0, "coalesced")
        obs.lifecycle(rec.uid, "settled", outcome="finished",
                      coalesced_into=leader)
        obs.flush_trace(rec.uid)
        if self.mgr is not None:
            self.mgr.release(rec.uid)
        self.store.incr("fsm:metric:jobs_finished")
        log_event("job_coalesced_fanout", uid=rec.uid, leader=leader)

    def _settle_follower_failure(self, rec: _Follower, exc) -> None:
        from spark_fsm_tpu.service import actors

        try:
            actors._record_failure(self.store, rec.uid, exc,
                                   keep_frontier=True,
                                   lease_mgr=self.mgr)
        except Exception as settle_exc:
            log_event("rescache_follower_settle_failed", uid=rec.uid,
                      error=str(settle_exc))

    def on_leader_terminal(self, uid: str) -> None:
        """Leader reached a NON-success terminal state (failure, abort,
        drain, steal, fence): its followers are independent clients —
        re-dispatch each through normal admission as a cold mine
        (possibly re-coalescing onto a fresh leader).  Any follower
        whose re-dispatch fails gets a durable failure — never a stuck
        uid."""
        for rec in self._pop_followers(uid):
            try:
                # the follower's OWN abort signals are owed first, same
                # as the fan-out path: a cancel the client was already
                # told "cancelling" about, or a deadline spent waiting
                # on the leader, must not be papered over by a fresh
                # cold mine
                jobctl.check_entry(rec.ctl)
            except jobctl.JobAborted as exc:
                self._settle_follower_failure(rec, exc)
                continue
            try:
                if rec.ctl.deadline is not None:
                    # the re-dispatch re-registers the control entry:
                    # carry the REMAINING budget over, not a fresh one
                    rec.req.data["deadline_s"] = repr(max(
                        0.001, rec.ctl.deadline - time.monotonic()))
                # tear down follower-side state so the fresh admission
                # starts clean (its journal entry would 409 the submit)
                self.store.journal_clear(rec.uid)
                jobctl.release_entry(rec.ctl)
                if self.mgr is not None:
                    self.mgr.release(rec.uid)
                obs.lifecycle(rec.uid, "uncoalesced", leader=uid)
                obs.flush_trace(rec.uid)
                self.miner.submit(rec.req)
                log_event("job_uncoalesced", uid=rec.uid, leader=uid)
            except Exception as exc:
                self._settle_follower_failure(rec, RuntimeError(
                    f"coalesced leader {uid!r} did not finish and the "
                    f"cold re-dispatch failed: {exc}"))

    # ----------------------------------------------------- entry store/LRU

    def _store_entry(self, req: ServiceRequest,
                     ctl: Optional[jobctl.JobControl], plugin, results,
                     stats: dict) -> None:
        from spark_fsm_tpu.service import plugins
        from spark_fsm_tpu.utils.canonical import (sort_patterns,
                                                   sort_rules)

        fp = ctl.dataset_fp if ctl is not None else None
        if fp is None:
            return  # fingerprint never landed: nothing to key on
        faults.fault_site("rescache.store", uid=req.uid,
                          key=entry_key(fp, plugin.name))
        n = int(stats.get("sequences") or 0)
        params = plugins.effective_params(req, n_sequences=n)
        if self.mgr is not None:
            # fenced like the result sink: a superseded holder must not
            # publish a cache entry over the adopter's
            self.mgr.fence(req.uid)
        if plugin.kind == "patterns":
            payload = model.serialize_patterns(sort_patterns(results))
        else:
            payload = model.serialize_rules(sort_rules(results))
        # the rule-set digest the prediction plane keys its compiled
        # artifacts on (ops/rule_trie.rules_digest over the SAME payload
        # string) — stored on the entry AND the LRU sidecar so the
        # stats/admin surface can audit (fingerprint, digest) pairs
        # without pulling payloads off the store
        from spark_fsm_tpu.ops.rule_trie import rules_digest

        digest = rules_digest(payload)
        ent = json.dumps({
            "algo": plugin.name, "kind": plugin.kind, "params": params,
            "n_sequences": n, "uid": req.uid, "digest": digest,
            "ts": round(time.time(), 3),
            # the mining job's recorded device cost (service/usage.py):
            # what a future serve from this entry AVOIDS — the usage
            # plane prices exact/dominated/coalesced credits from it
            "usage": stats.get("usage"),
            "payload": payload})
        # enveloped (utils/envelope.py) — entry FIRST, sidecar second:
        # a kill between the two leaves an intact entry whose sidecar
        # the scrubber (or the next serve-miss scrub) re-derives
        self.store.set(entry_key(fp, plugin.name), envelope.wrap(ent))
        self.store.set(_lru_key(fp, plugin.name), envelope.wrap(json.dumps(
            {"ts": time.time(), "bytes": len(ent), "digest": digest})))
        _BYTES_TOTAL.inc(len(ent))
        log_event("rescache_entry_stored", uid=req.uid, fp=fp[:16],
                  algo=plugin.name, bytes=len(ent))
        self._evict()

    def _meta_rows(self):
        """(last_used_ts, entry_key, tail, byte_size, digest) for every
        resident entry, read from the LRU sidecars — the eviction sweep
        and the stats endpoint must not pull full payloads off the
        store (at the default budget that would be up to 64 MiB per
        pass over a Redis backend).  An entry whose sidecar is
        missing/corrupt falls back to one payload read (digest absent
        for pre-sidecar-format entries)."""
        rows = []
        for key in self.store.scan_iter("fsm:rescache:"):
            tail = key[len("fsm:rescache:"):]
            ts, size, digest = 0.0, None, None
            side, _sv = envelope.unwrap(
                self.store.peek("fsm:rescache-lru:" + tail))
            if side:
                try:
                    meta = json.loads(side)
                    ts = float(meta.get("ts") or 0.0)
                    size = int(meta["bytes"])
                    digest = meta.get("digest")
                except (ValueError, TypeError, KeyError):
                    pass
            if size is None:
                raw = self.store.peek(key)
                if raw is None:
                    continue
                payload, _v = envelope.unwrap(raw)
                size = len(payload) if payload is not None else len(raw)
            rows.append((ts, key, tail, size, digest))
        return rows

    def _evict(self) -> None:
        """LRU byte-budget sweep over a cursor SCAN (never KEYS): drop
        the least-recently-used entries until the resident bytes fit
        ``max_bytes``.  Eviction is plain DELs — a concurrent serve
        that loses the race simply misses and mines cold."""
        rows = self._meta_rows()
        total = sum(size for _, _, _, size, _ in rows)
        if self.max_bytes:
            for ts, key, tail, size, _ in sorted(
                    rows, key=lambda r: (r[0], r[1])):
                if total <= self.max_bytes:
                    break
                self.store.delete(key)
                self.store.delete("fsm:rescache-lru:" + tail)
                total -= size
                _EVICTIONS.inc()
                log_event("rescache_evicted", key=key, bytes=size)
        _BYTES.set(total)

    # ------------------------------------------------------------ admin

    def stats(self) -> dict:
        with self._lock:
            leaders = len(self._by_leader)
            followers = sum(len(s["followers"])
                            for s in self._by_leader.values())
        try:
            rows = self._meta_rows()
            entries = len(rows)
            bytes_total = sum(size for _, _, _, size, _ in rows)
            # auditable per-entry identity (ISSUE 17 satellite): the
            # dataset fingerprint + algorithm the entry serves under and
            # the rule-set digest the prediction plane's artifact cache
            # keys on — an operator can now line /admin/predictor's
            # resident digests up against the cache that fed them
            detail = []
            for ts, _, tail, size, digest in sorted(rows, reverse=True,
                                                    key=lambda r: r[0]):
                fp, _, algo = tail.rpartition(":")
                detail.append({"fingerprint": fp, "algo": algo,
                               "digest": digest, "bytes": size,
                               "ts": round(ts, 3)})
        except Exception:
            entries = bytes_total = detail = None  # store down: stay
            # readable
        return {
            "enabled": True,
            "coalesce": self.coalesce_enabled,
            "dominance": self.dominance_enabled,
            "max_bytes": self.max_bytes,
            "entries": entries,
            "bytes": bytes_total,
            "entries_detail": detail,
            "inflight_leaders": leaders,
            "inflight_followers": followers,
            "counters": {
                "hits": _HITS.total(),
                "dominated_serves": _DOMINATED.total(),
                "misses": _MISSES.total(),
                "coalesced": _COALESCED.total(),
                "evictions": _EVICTIONS.total(),
                "errors": _ERRORS.total(),
            },
        }


# ----------------------------------------------------- dominance predicates

def _servable(ent: dict, want: dict
              ) -> Optional[Tuple[str, str, int]]:
    """(payload_json, mode, n_results) when the cached entry ``ent``
    can answer the effective params ``want`` EXACTLY, else None.  The
    conservative per-algorithm predicates — docs/DESIGN.md proves each;
    tests/test_resultcache.py pins parity against cold mines and the
    deliberately non-dominated misses."""
    if ent.get("algo") != want.get("algo"):
        return None
    if ent.get("kind") == "patterns":
        return _servable_patterns(ent, want)
    if ent.get("kind") == "rules":
        return _servable_rules(ent, want)
    return None


def _servable_patterns(ent: dict, want: dict
                       ) -> Optional[Tuple[str, str, int]]:
    from spark_fsm_tpu.data.vertical import abs_minsup

    have = ent["params"]
    if (have.get("maxgap"), have.get("maxwindow")) != \
            (want.get("maxgap"), want.get("maxwindow")):
        # constraints must match EXACTLY: supports change under a
        # tighter gap/window, so filtering cannot reproduce a cold mine
        return None
    m0 = have.get("minsup_abs")
    if m0 is None:
        return None
    m1 = want.get("minsup_abs")
    if m1 is None:
        # relative support: same fingerprint => same |DB|, so the
        # cached entry's sequence count resolves it
        m1 = abs_minsup(float(want["support"]), int(ent["n_sequences"]))
    if m1 == m0:
        return ent["payload"], "exact", _payload_len(ent)
    if m1 < m0:
        return None  # lower minsup admits patterns the cached run pruned
    pats = model.deserialize_patterns(ent["payload"])
    kept = [(p, s) for p, s in pats if s >= m1]
    return model.serialize_patterns(kept), "dominated", len(kept)


def _servable_rules(ent: dict, want: dict
                    ) -> Optional[Tuple[str, str, int]]:
    have = ent["params"]
    k0, k1 = int(have["k"]), int(want["k"])
    n0, d0 = _conf_frac(have["minconf"])
    n1, d1 = _conf_frac(want["minconf"])
    s0, s1 = have.get("max_side"), want.get("max_side")
    same_conf = n0 * d1 == n1 * d0
    same_side = s0 == s1
    if k1 == k0 and same_conf and same_side:
        return ent["payload"], "exact", _payload_len(ent)
    if k1 > k0:
        return None  # a bigger k needs rules the cached run cut
    if n1 * d0 < n0 * d1:
        return None  # lower minconf admits rules the cached run pruned
    if s0 is not None and (s1 is None or int(s1) > int(s0)):
        return None  # looser side bound needs unexplored rules
    rules = model.deserialize_rules(ent["payload"])
    # the cached run's own tie-inclusive threshold: min support when
    # the heap filled (>= k0 rules), else the run was EXHAUSTIVE (it
    # returned every qualifying rule — nothing was support-pruned)
    exhaustive = len(rules) < k0
    s_k0 = min((r[2] for r in rules), default=0)
    cand = [r for r in rules
            if r[2] * d1 >= n1 * r[3]  # conf >= minconf', exact
            and (s1 is None or (len(r[0]) <= int(s1)
                                and len(r[1]) <= int(s1)))]
    if len(cand) >= k1:
        sups = sorted((r[2] for r in cand), reverse=True)
        s_k1 = sups[k1 - 1]
        if not exhaustive and s_k1 < s_k0:
            # rules the cached run support-pruned (sup < s_k0) could
            # enter this weaker top-k: refuse, mine cold
            return None
        kept = [r for r in cand if r[2] >= s_k1]
    else:
        if not exhaustive:
            return None  # the full qualifying set was never materialized
        kept = cand
    return model.serialize_rules(kept), "dominated", len(kept)


def _payload_len(ent: dict) -> int:
    try:
        return len(json.loads(ent["payload"]))
    except Exception:
        return 0
