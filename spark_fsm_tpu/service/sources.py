"""Sequence sources — the reference's pluggable L1/L2 data layer.

The reference builds ``RDD[(Int, String)]`` sequence databases from
Elasticsearch, JDBC, flat files, and Piwik (SURVEY.md sec 1 L1, sec 2
"Sequence sources"); the rebuild keeps the same selection contract
(``source`` request param) and SPMF line format but returns an in-memory
``SequenceDB`` — device sharding happens downstream in the engines, which
is this framework's analog of Spark partitioning (SURVEY.md sec 2.2).

Registered sources:
  FILE     — SPMF-format text file (``path`` param).
  INLINE   — SPMF text embedded in the request (``data`` param's
             ``sequences`` key); handy for tests and small jobs.
  TRACKED  — events previously ingested via /track for a topic, grouped
             into per-(site,user) sequences ordered by timestamp: the
             reference's track->mine loop without an external store.
  SYNTH    — seeded synthetic DB (no-egress stand-in for the public
             benchmark datasets; see data/synth.py).
  JDBC     — SQL database via stdlib sqlite3 (``db``/``url`` + ``query``
             or ``table``), with the same field-role mapping as TRACKED.
  ELASTIC / PIWIK — interface stubs: constructing them raises a clear
             error in this sandbox (no network egress), but the registry
             seam and parameter names match SURVEY.md.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Tuple

from spark_fsm_tpu.data.spmf import SequenceDB, load_spmf, parse_spmf
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore


class SourceError(ValueError):
    pass


def file_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    path = req.param("path")
    if not path:
        raise SourceError("FILE source needs a 'path' parameter")
    return load_spmf(path)


def inline_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    text = req.param("sequences")
    if text is None:
        raise SourceError("INLINE source needs a 'sequences' parameter")
    return parse_spmf(text)


ROLES = ("site", "user", "timestamp", "group", "item")


def field_map(store: ResultStore, topic: str) -> Dict[str, str]:
    """role -> event-field-name mapping for a topic.

    The reference's register step exists precisely to map *arbitrary*
    source fields onto the site/user/timestamp/group/item roles (SURVEY.md
    sec 2 "Registrar / field spec", sec 3.4).  A registered spec for the
    topic (``/register``, stored as ``fsm:fields:<topic>``) supplies the
    mapping; unregistered roles default to their own name.
    """
    mapping = {r: r for r in ROLES}
    spec_json = store.fields(topic)
    if spec_json:
        try:
            spec = json.loads(spec_json)
        except ValueError:
            spec = {}
        for role in ROLES:
            name = spec.get(role)
            if isinstance(name, str) and name:
                mapping[role] = name
    return mapping


def events_to_db(events: List[dict], fm: Dict[str, str],
                 origin: str) -> SequenceDB:
    """Group role-mapped events into an SPMF sequence database.

    Shared by the TRACKED and JDBC sources: sequence key = (site, user);
    each distinct group id forms ONE itemset (even if its rows interleave
    in time with other groups), and itemsets are ordered by the group's
    first timestamp — the reference's field-spec semantics (SURVEY.md
    sec 2 "Registrar / field spec").
    """
    sessions: Dict[Tuple[str, str], Dict[int, List[Tuple[int, int]]]] = {}
    for ev in events:
        key = (str(ev.get(fm["site"], "")), str(ev.get(fm["user"], "")))
        ts_raw = ev.get(fm["timestamp"])
        ts = int(ts_raw) if ts_raw not in (None, "") else 0
        g_raw = ev.get(fm["group"])
        group = int(g_raw) if g_raw not in (None, "") else ts
        if fm["item"] not in ev or ev[fm["item"]] is None:
            # spec registered/changed after this event was recorded
            raise SourceError(
                f"{origin} event has no field {fm['item']!r} (the "
                f"registered 'item' role); event keys: {sorted(ev)} — "
                f"fix the /register spec or the source data")
        item = int(ev[fm["item"]])
        sessions.setdefault(key, {}).setdefault(group, []).append((ts, item))
    db: SequenceDB = []
    for key in sorted(sessions):
        groups = sessions[key]
        # itemset order = (first timestamp of the group, group id)
        order = sorted(groups, key=lambda g: (min(ts for ts, _ in groups[g]), g))
        itemsets = [tuple(sorted({item for _, item in groups[g]}))
                    for g in order]
        if itemsets:
            db.append(tuple(itemsets))
    return db


def tracked_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """Events ingested via /track, grouped per the topic's field spec."""
    topic = req.param("topic", "item")
    events = store.tracked(topic)
    if not events:
        raise SourceError(f"no tracked events for topic {topic!r}")
    fm = field_map(store, topic)
    return events_to_db([json.loads(e) for e in events], fm,
                        origin=f"tracked topic {topic!r}")


def jdbc_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """SQL database source — the reference's JdbcSource seam, implemented
    on stdlib sqlite3 (the sandbox's JDBC-reachable database).

    Params: ``db`` = sqlite file path (or ``url`` = ``sqlite:///path``),
    plus ``query`` (SQL whose result columns carry the role fields) or
    ``table`` (SELECT * FROM table).  Column-name -> role mapping comes
    from the topic's registered field spec, exactly like TRACKED.
    """
    url = req.param("url")
    path = req.param("db")
    if url:
        if not url.startswith("sqlite:///"):
            raise SourceError(
                f"JDBC url {url!r} unsupported: this build speaks "
                f"sqlite:///path (no network egress for remote databases)")
        path = url[len("sqlite:///"):]
    if not path:
        raise SourceError("JDBC source needs a 'db' (sqlite file path) "
                          "or 'url' (sqlite:///path) parameter")
    query = req.param("query")
    table = req.param("table")
    if query is None:
        if not table:
            raise SourceError("JDBC source needs a 'query' or 'table' "
                              "parameter")
        if not table.replace("_", "").isalnum():
            raise SourceError(f"invalid table name {table!r}")
        query = f"SELECT * FROM {table}"

    import sqlite3

    try:
        # open read-only so a typo'd path errors instead of creating a db;
        # percent-encode the path so '?', '#', '%' in filenames survive the
        # URI parse
        from urllib.parse import quote
        conn = sqlite3.connect(f"file:{quote(path)}?mode=ro", uri=True)
    except sqlite3.OperationalError as exc:
        raise SourceError(f"cannot open sqlite db {path!r}: {exc}") from exc
    try:
        cur = conn.execute(query)
        if cur.description is None:  # empty/comment-only/non-SELECT query
            raise SourceError(f"JDBC query returned no result set: {query!r}")
        cols = [d[0] for d in cur.description]
        events = [dict(zip(cols, row)) for row in cur.fetchall()]
    except sqlite3.Error as exc:
        raise SourceError(f"JDBC query failed: {exc}") from exc
    finally:
        conn.close()
    if not events:
        raise SourceError(f"JDBC query returned no rows: {query!r}")
    fm = field_map(store, req.param("topic", "item"))
    return events_to_db(events, fm, origin="JDBC row")


def synth_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    from spark_fsm_tpu.data import synth

    name = req.param("dataset", "bms_webview1")
    scale = float(req.param("scale", "0.01"))
    gen = getattr(synth, f"{name}_like", None)
    if gen is None:
        raise SourceError(f"unknown synthetic dataset {name!r}")
    return gen(scale=scale)


def _stub(name: str, needs: str) -> Callable[[ServiceRequest, ResultStore], SequenceDB]:
    def raise_stub(req: ServiceRequest, store: ResultStore) -> SequenceDB:
        raise SourceError(
            f"{name} source is an interface stub in this build: {needs}. "
            f"Use FILE/INLINE/TRACKED/SYNTH, or register a client via "
            f"sources.register()."
        )

    return raise_stub


SOURCES: Dict[str, Callable[[ServiceRequest, ResultStore], SequenceDB]] = {
    "FILE": file_source,
    "INLINE": inline_source,
    "TRACKED": tracked_source,
    "SYNTH": synth_source,
    # reference parity: ElasticSource / JdbcSource / PiwikSource seams
    "ELASTIC": _stub("ELASTIC", "requires an Elasticsearch endpoint"),
    "JDBC": jdbc_source,
    "PIWIK": _stub("PIWIK", "requires a Piwik analytics database"),
}


def register(name: str,
             fn: Callable[[ServiceRequest, ResultStore], SequenceDB]) -> None:
    SOURCES[name.upper()] = fn


def get_db(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    name = (req.param("source") or "FILE").upper()
    if name not in SOURCES:
        raise SourceError(f"unknown source {name!r}")
    return SOURCES[name](req, store)
