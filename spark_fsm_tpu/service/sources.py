"""Sequence sources — the reference's pluggable L1/L2 data layer.

The reference builds ``RDD[(Int, String)]`` sequence databases from
Elasticsearch, JDBC, flat files, and Piwik (SURVEY.md sec 1 L1, sec 2
"Sequence sources"); the rebuild keeps the same selection contract
(``source`` request param) and SPMF line format but returns an in-memory
``SequenceDB`` — device sharding happens downstream in the engines, which
is this framework's analog of Spark partitioning (SURVEY.md sec 2.2).

Registered sources:
  FILE     — SPMF-format text file (``path`` param).
  INLINE   — SPMF text embedded in the request (``data`` param's
             ``sequences`` key); handy for tests and small jobs.
  TRACKED  — events previously ingested via /track for a topic, grouped
             into per-(site,user) sequences ordered by timestamp: the
             reference's track->mine loop without an external store.
  SYNTH    — seeded synthetic DB (no-egress stand-in for the public
             benchmark datasets; see data/synth.py).
  ELASTIC / JDBC / PIWIK — interface stubs: constructing them raises a
             clear error in this sandbox (no network egress / no driver),
             but the registry seam and parameter names match SURVEY.md.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Tuple

from spark_fsm_tpu.data.spmf import SequenceDB, load_spmf, parse_spmf
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore


class SourceError(ValueError):
    pass


def file_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    path = req.param("path")
    if not path:
        raise SourceError("FILE source needs a 'path' parameter")
    return load_spmf(path)


def inline_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    text = req.param("sequences")
    if text is None:
        raise SourceError("INLINE source needs a 'sequences' parameter")
    return parse_spmf(text)


def tracked_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """Group tracked events into sequences.

    Events are JSON objects with the registered field roles: site, user,
    timestamp, group (itemset id within a session), item.  Sequence key =
    (site, user); itemsets group by 'group' (or timestamp when absent),
    ordered by timestamp — the reference's field-spec semantics
    (SURVEY.md sec 2 "Registrar / field spec").
    """
    topic = req.param("topic", "item")
    events = store.tracked(topic)
    if not events:
        raise SourceError(f"no tracked events for topic {topic!r}")
    sessions: Dict[Tuple[str, str], List[Tuple[int, int, int]]] = {}
    for ev_json in events:
        ev = json.loads(ev_json)
        key = (str(ev.get("site", "")), str(ev.get("user", "")))
        ts = int(ev.get("timestamp", 0))
        group = int(ev.get("group", ts))
        item = int(ev["item"])
        sessions.setdefault(key, []).append((ts, group, item))
    db: SequenceDB = []
    for key in sorted(sessions):
        rows = sorted(sessions[key])
        itemsets: List[Tuple[int, ...]] = []
        cur_group = None
        cur: set = set()
        for ts, group, item in rows:
            if cur_group is None or group != cur_group:
                if cur:
                    itemsets.append(tuple(sorted(cur)))
                cur = set()
                cur_group = group
            cur.add(item)
        if cur:
            itemsets.append(tuple(sorted(cur)))
        if itemsets:
            db.append(tuple(itemsets))
    return db


def synth_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    from spark_fsm_tpu.data import synth

    name = req.param("dataset", "bms_webview1")
    scale = float(req.param("scale", "0.01"))
    gen = getattr(synth, f"{name}_like", None)
    if gen is None:
        raise SourceError(f"unknown synthetic dataset {name!r}")
    return gen(scale=scale)


def _stub(name: str, needs: str) -> Callable[[ServiceRequest, ResultStore], SequenceDB]:
    def raise_stub(req: ServiceRequest, store: ResultStore) -> SequenceDB:
        raise SourceError(
            f"{name} source is an interface stub in this build: {needs}. "
            f"Use FILE/INLINE/TRACKED/SYNTH, or register a client via "
            f"sources.register()."
        )

    return raise_stub


SOURCES: Dict[str, Callable[[ServiceRequest, ResultStore], SequenceDB]] = {
    "FILE": file_source,
    "INLINE": inline_source,
    "TRACKED": tracked_source,
    "SYNTH": synth_source,
    # reference parity: ElasticSource / JdbcSource / PiwikSource seams
    "ELASTIC": _stub("ELASTIC", "requires an Elasticsearch endpoint"),
    "JDBC": _stub("JDBC", "requires a JDBC-reachable database"),
    "PIWIK": _stub("PIWIK", "requires a Piwik analytics database"),
}


def register(name: str,
             fn: Callable[[ServiceRequest, ResultStore], SequenceDB]) -> None:
    SOURCES[name.upper()] = fn


def get_db(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    name = (req.param("source") or "FILE").upper()
    if name not in SOURCES:
        raise SourceError(f"unknown source {name!r}")
    return SOURCES[name](req, store)
