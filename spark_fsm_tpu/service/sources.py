"""Sequence sources — the reference's pluggable L1/L2 data layer.

The reference builds ``RDD[(Int, String)]`` sequence databases from
Elasticsearch, JDBC, flat files, and Piwik (SURVEY.md sec 1 L1, sec 2
"Sequence sources"); the rebuild keeps the same selection contract
(``source`` request param) and SPMF line format but returns an in-memory
``SequenceDB`` — device sharding happens downstream in the engines, which
is this framework's analog of Spark partitioning (SURVEY.md sec 2.2).

Registered sources:
  FILE     — SPMF-format text file (``path`` param).
  INLINE   — SPMF text embedded in the request (``data`` param's
             ``sequences`` key); handy for tests and small jobs.
  TRACKED  — events previously ingested via /track for a topic, grouped
             into per-(site,user) sequences ordered by timestamp: the
             reference's track->mine loop without an external store.
  SYNTH    — seeded synthetic DB (no-egress stand-in for the public
             benchmark datasets; see data/synth.py).
  JDBC     — SQL database via stdlib sqlite3 (``db``/``url`` + ``query``
             or ``table``), with the same field-role mapping as TRACKED.
  ELASTIC  — Elasticsearch search/scroll HTTP API (``url`` + ``index``),
             hit ``_source`` fields role-mapped like TRACKED/JDBC.
  PIWIK    — Piwik analytics DB export (sqlite): the ecommerce item log
             grouped into per-visitor purchase sequences.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Tuple

from spark_fsm_tpu.data.spmf import SequenceDB, load_spmf, parse_spmf
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import ResultStore


class SourceError(ValueError):
    pass


def file_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    path = req.param("path")
    if not path:
        raise SourceError("FILE source needs a 'path' parameter")
    return load_spmf(path)


def inline_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    text = req.param("sequences")
    if text is None:
        raise SourceError("INLINE source needs a 'sequences' parameter")
    return parse_spmf(text)


ROLES = ("site", "user", "timestamp", "group", "item")


def field_map(store: ResultStore, topic: str) -> Dict[str, str]:
    """role -> event-field-name mapping for a topic.

    The reference's register step exists precisely to map *arbitrary*
    source fields onto the site/user/timestamp/group/item roles (SURVEY.md
    sec 2 "Registrar / field spec", sec 3.4).  A registered spec for the
    topic (``/register``, stored as ``fsm:fields:<topic>``) supplies the
    mapping; unregistered roles default to their own name.
    """
    mapping = {r: r for r in ROLES}
    spec_json = store.fields(topic)
    if spec_json:
        try:
            spec = json.loads(spec_json)
        except ValueError:
            spec = {}
        for role in ROLES:
            name = spec.get(role)
            if isinstance(name, str) and name:
                mapping[role] = name
    return mapping


def events_to_db(events: List[dict], fm: Dict[str, str],
                 origin: str) -> SequenceDB:
    """Group role-mapped events into an SPMF sequence database.

    Shared by the TRACKED and JDBC sources: sequence key = (site, user);
    each distinct group id forms ONE itemset (even if its rows interleave
    in time with other groups), and itemsets are ordered by the group's
    first timestamp — the reference's field-spec semantics (SURVEY.md
    sec 2 "Registrar / field spec").
    """
    # group key = (tag, id): tag 0 for numeric ids, 1 for string ids, so
    # mixed id types keep one deterministic sort order
    sessions: Dict[Tuple[str, str], Dict[tuple, List[Tuple[int, int]]]] = {}
    for ev in events:
        key = (str(ev.get(fm["site"], "")), str(ev.get(fm["user"], "")))
        ts_raw = ev.get(fm["timestamp"])
        ts = int(ts_raw) if ts_raw not in (None, "") else 0
        g_raw = ev.get(fm["group"])
        # group ids may be arbitrary strings (e.g. Piwik order ids like
        # 'ORD-1001'); the tagged tuple keeps numeric and string ids in
        # one deterministic sort order for the first-timestamp tiebreak
        if g_raw in (None, ""):
            group = (0, ts)
        else:
            try:
                group = (0, int(g_raw))
            except (TypeError, ValueError):
                group = (1, str(g_raw))
        if fm["item"] not in ev or ev[fm["item"]] is None:
            # spec registered/changed after this event was recorded
            raise SourceError(
                f"{origin} event has no field {fm['item']!r} (the "
                f"registered 'item' role); event keys: {sorted(ev)} — "
                f"fix the /register spec or the source data")
        item = int(ev[fm["item"]])
        sessions.setdefault(key, {}).setdefault(group, []).append((ts, item))
    db: SequenceDB = []
    for key in sorted(sessions):
        groups = sessions[key]
        # itemset order = (first timestamp of the group, group id)
        order = sorted(groups, key=lambda g: (min(ts for ts, _ in groups[g]), g))
        itemsets = [tuple(sorted({item for _, item in groups[g]}))
                    for g in order]
        if itemsets:
            db.append(tuple(itemsets))
    return db


def tracked_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """Events ingested via /track, grouped per the topic's field spec."""
    topic = req.param("topic", "item")
    events = store.tracked(topic)
    if not events:
        raise SourceError(f"no tracked events for topic {topic!r}")
    fm = field_map(store, topic)
    return events_to_db([json.loads(e) for e in events], fm,
                        origin=f"tracked topic {topic!r}")


def _sqlite_path(req: ServiceRequest, source_name: str) -> str:
    """Resolve the ``db``/``url`` params both sqlite-backed sources share."""
    url = req.param("url")
    path = req.param("db")
    if url:
        if not url.startswith("sqlite:///"):
            raise SourceError(
                f"{source_name} url {url!r} unsupported: this build speaks "
                f"sqlite:///path (no network egress for remote databases)")
        path = url[len("sqlite:///"):]
    if not path:
        raise SourceError(f"{source_name} source needs a 'db' (sqlite file "
                          f"path) or 'url' (sqlite:///path) parameter")
    return path


def jdbc_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """SQL database source — the reference's JdbcSource seam, implemented
    on stdlib sqlite3 (the sandbox's JDBC-reachable database).

    Params: ``db`` = sqlite file path (or ``url`` = ``sqlite:///path``),
    plus ``query`` (SQL whose result columns carry the role fields) or
    ``table`` (SELECT * FROM table).  Column-name -> role mapping comes
    from the topic's registered field spec, exactly like TRACKED.
    """
    path = _sqlite_path(req, "JDBC")
    query = req.param("query")
    table = req.param("table")
    if query is None:
        if not table:
            raise SourceError("JDBC source needs a 'query' or 'table' "
                              "parameter")
        if not table.replace("_", "").isalnum():
            raise SourceError(f"invalid table name {table!r}")
        query = f"SELECT * FROM {table}"
    events = _sqlite_events(path, query, ())
    if not events:
        raise SourceError(f"JDBC query returned no rows: {query!r}")
    fm = field_map(store, req.param("topic", "item"))
    return events_to_db(events, fm, origin="JDBC row")


def _sqlite_events(path: str, query: str, params: tuple) -> List[dict]:
    """Run one SQL query read-only; rows as column-name dicts."""
    import sqlite3

    try:
        # open read-only so a typo'd path errors instead of creating a db;
        # percent-encode the path so '?', '#', '%' in filenames survive the
        # URI parse
        from urllib.parse import quote
        conn = sqlite3.connect(f"file:{quote(path)}?mode=ro", uri=True)
    except sqlite3.OperationalError as exc:
        raise SourceError(f"cannot open sqlite db {path!r}: {exc}") from exc
    try:
        cur = conn.execute(query, params)
        if cur.description is None:  # empty/comment-only/non-SELECT query
            raise SourceError(f"query returned no result set: {query!r}")
        cols = [d[0] for d in cur.description]
        return [dict(zip(cols, row)) for row in cur.fetchall()]
    except sqlite3.Error as exc:
        raise SourceError(f"query failed: {exc}") from exc
    finally:
        conn.close()


def elastic_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """Elasticsearch source — the reference's ElasticSource seam, speaking
    the real search/scroll HTTP API via stdlib urllib.

    Params: ``url`` = ``http(s)://host:port``, ``index``; optional
    ``query`` (JSON ES query object; default match_all) and ``page_size``
    (scroll page, default 1000).  Hit ``_source`` fields map onto the
    site/user/timestamp/group/item roles via the topic's registered field
    spec, exactly like TRACKED/JDBC.  Protocol-tested against an
    in-process mini-ES (tests/test_elastic_piwik_sources.py); the same
    bytes reach a production cluster.
    """
    import urllib.error
    import urllib.request

    url = (req.param("url") or "").rstrip("/")
    index = req.param("index")
    if not url.startswith(("http://", "https://")) or not index:
        raise SourceError("ELASTIC source needs 'url' (http(s)://host:port) "
                          "and 'index' parameters")
    if "/" in index or index.startswith(("_", "-")):
        raise SourceError(f"invalid index name {index!r}")
    try:
        page_size = int(req.param("page_size", "1000"))
        es_query = json.loads(req.param("query") or '{"match_all": {}}')
    except ValueError as exc:
        raise SourceError(f"bad ELASTIC parameter: {exc}") from exc

    def post_json(endpoint: str, obj: dict) -> dict:
        request = urllib.request.Request(
            endpoint, data=json.dumps(obj).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(request, timeout=30) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise SourceError(f"Elasticsearch request to {endpoint} "
                              f"failed: {exc}") from exc

    events: List[dict] = []
    scroll_id = None
    try:
        page = post_json(f"{url}/{index}/_search?scroll=1m",
                         {"size": page_size, "query": es_query})
        while True:
            # capture the scroll id FIRST: even a zero-hit search opened a
            # server-side scroll context that the finally must free
            scroll_id = page.get("_scroll_id", scroll_id)
            hits = page["hits"]["hits"]
            if not hits:
                break  # ES's documented scroll termination: an EMPTY page
            # (a short page is NOT the end — multi-shard scrolls may
            # legitimately return fewer than `size` hits mid-scroll)
            events.extend(h["_source"] for h in hits)
            if page.get("_scroll_id") is None:
                break
            page = post_json(f"{url}/_search/scroll",
                             {"scroll": "1m", "scroll_id": scroll_id})
    except (KeyError, TypeError) as exc:
        raise SourceError(
            f"malformed Elasticsearch response (missing {exc})") from exc
    finally:
        if scroll_id is not None:
            # free the scroll context (clusters cap open scrolls at ~500);
            # best-effort — the 1m keepalive reaps it anyway
            request = urllib.request.Request(
                f"{url}/_search/scroll", method="DELETE",
                data=json.dumps({"scroll_id": scroll_id}).encode("utf-8"),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(request, timeout=10).close()
            except (urllib.error.URLError, OSError):
                pass
    if not events:
        raise SourceError(f"Elasticsearch query matched no documents in "
                          f"index {index!r}")
    fm = field_map(store, req.param("topic", "item"))
    return events_to_db(events, fm, origin="Elasticsearch hit")


def piwik_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    """Piwik analytics source — the reference's PiwikSource seam.

    Reads the ecommerce item log (``piwik_log_conversion_item``: one row
    per purchased item) the way the reference mines Piwik commerce data:
    site = idsite, user = idvisitor, timestamp = server_time, itemset
    group = idorder, item = idaction_sku.  Params: ``db``/``url`` =
    sqlite path of the (exported) Piwik database, optional ``idsite``
    filter.  server_time may be a DATETIME string or an epoch integer.
    """
    path = _sqlite_path(req, "PIWIK")
    idsite = req.param("idsite")
    # DATETIME strings go through strftime('%s', ...); numeric values are
    # epochs and pass through directly.  The typeof() dispatch matters:
    # strftime on an INTEGER would interpret it as a Julian day number
    # (strftime('%s', 2000000) = -38066760000, not NULL), so a COALESCE
    # fallback would silently mis-order mixed-type columns.
    query = (
        "SELECT idsite AS site, idvisitor AS user, "
        "CASE WHEN typeof(server_time) = 'text' "
        # text: DATETIME via strftime; COALESCE keeps TEXT-affinity numeric
        # epochs (e.g. a CSV import) instead of collapsing them to NULL
        "THEN COALESCE(CAST(strftime('%s', server_time) AS INTEGER), "
        "CAST(server_time AS INTEGER)) "
        "ELSE CAST(server_time AS INTEGER) END AS timestamp, "
        'idorder AS "group", idaction_sku AS item '
        "FROM piwik_log_conversion_item")
    params: tuple = ()
    if idsite is not None:
        query += " WHERE idsite = ?"
        try:
            params = (int(idsite),)
        except ValueError as exc:
            raise SourceError(f"bad idsite {idsite!r}: {exc}") from exc
    events = _sqlite_events(path, query, params)
    if not events:
        raise SourceError("no Piwik conversion items"
                          + (f" for idsite {idsite}" if idsite else ""))
    # roles are fixed by the Piwik schema (aliased above) — no field spec
    return events_to_db(events, {r: r for r in ROLES}, origin="Piwik row")


def synth_source(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    from spark_fsm_tpu.data import synth

    name = req.param("dataset", "bms_webview1")
    scale = float(req.param("scale", "0.01"))
    gen = getattr(synth, f"{name}_like", None)
    if gen is None:
        raise SourceError(f"unknown synthetic dataset {name!r}")
    return gen(scale=scale)


SOURCES: Dict[str, Callable[[ServiceRequest, ResultStore], SequenceDB]] = {
    "FILE": file_source,
    "INLINE": inline_source,
    "TRACKED": tracked_source,
    "SYNTH": synth_source,
    "ELASTIC": elastic_source,
    "JDBC": jdbc_source,
    "PIWIK": piwik_source,
}


def register(name: str,
             fn: Callable[[ServiceRequest, ResultStore], SequenceDB]) -> None:
    SOURCES[name.upper()] = fn


def get_db(req: ServiceRequest, store: ResultStore) -> SequenceDB:
    name = (req.param("source") or "FILE").upper()
    if name not in SOURCES:
        raise SourceError(f"unknown source {name!r}")
    return SOURCES[name](req, store)
