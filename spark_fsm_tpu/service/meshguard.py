"""Topology-survival plane: partition-row health, mesh epochs, and the
crash-loop (poison-job) quarantine ledger.

Every committed failure domain so far — store blips (storeguard),
replica crashes (lease), corrupt durable state (integrity) — assumed
the device topology itself is immortal: a TPU host dropping out of the
partitioned 2-D mesh (or one partition row wedging past its watchdog)
failed the whole mine.  This module is the registry that turns "a chip
died" into "a slower mine":

- **Row health state machine** (healthy -> suspect -> dead): fed by the
  engines' existing failure surfaces — dispatch watchdog timeouts,
  ``device.dispatch`` / ``device.resident`` fault trips — plus an
  active zero-width probe per row (a ``device_put`` of an empty array
  on the row's own devices, riding the lease heartbeat).  The FIRST
  device-shaped trip only marks a row suspect; ``[meshguard]
  dead_after`` trips kill it.  A suspect row that answers a probe (or
  completes a round) heals back to healthy; a dead row never heals in
  place — operators replace hardware, they do not resurrect it.

- **Topology epochs**: every row death bumps a monotonic
  ``topology_epoch``.  Engines capture the epoch at construction and
  re-check it at each dispatch entry (``check_epoch``); the fusion
  broker does the same per wave — a launch planned against a stale
  mesh is REFUSED (``StaleTopology``) before it touches dead silicon,
  counted in ``fsm_mesh_stale_epoch_refused_total``.  Epoch + dead-row
  set publish on the lease heartbeat (``heartbeat_payload``) and merge
  from peers (``merge_peer``: max epoch wins, dead sets union), so the
  fleet agrees which rows are dead without a coordinator.

- **Poison-job quarantine ledger**: a job whose dataset
  deterministically crashes its holder rides lease adoption forever,
  burning every replica in turn.  ``recover_orphans`` counts adoption
  resubmits in the journal intent; past ``[cluster] max_adoptions``
  the job settles as a durable ``POISON:`` failure and this module
  writes the ``fsm:quarantine:{uid}`` record (surface ``"poison"``,
  enveloped, with the last holder's trace-spine tail as evidence).
  Admission refuses a quarantined uid with 409 until
  ``/admin/quarantine`` releases it — the helpers here are shared by
  service/actors.py and service/app.py.

Cost contract (the utils/faults pin): with ``[meshguard]`` disabled
(the default) every engine-side probe — ``note_row_fault``,
``note_row_ok``, ``current_epoch``, ``check_epoch`` — is ONE
module-global read, and dispatch behavior is byte-identical to a
build without the plane.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from spark_fsm_tpu.utils import envelope, faults, obs
from spark_fsm_tpu.utils.obs import log_event

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"

#: the quarantine surface that marks a crash-loop poison record (ISSUE
#: 18's integrity quarantines use "journal"/"checkpoint"/... — only
#: ``poison`` records block re-admission)
POISON_SURFACE = "poison"

QUARANTINE_PREFIX = "fsm:quarantine:"

_EPOCH = obs.REGISTRY.gauge(
    "fsm_mesh_epoch",
    "current topology epoch (bumps on every partition-row death)")
_ROWS_DEAD = obs.REGISTRY.gauge(
    "fsm_mesh_rows_dead", "partition rows currently fenced as dead")
_TRANSITIONS = obs.REGISTRY.counter(
    "fsm_mesh_row_transitions_total",
    "partition-row health transitions, by destination state")
_PROBES = obs.REGISTRY.counter(
    "fsm_mesh_probes_total",
    "active zero-width row probes, by outcome")
_REPLANS = obs.REGISTRY.counter(
    "fsm_mesh_replans_total",
    "degraded re-plans (replan_surviving adoptions of dead rows' "
    "classes onto survivors)")
_STALE_REFUSED = obs.REGISTRY.counter(
    "fsm_mesh_stale_epoch_refused_total",
    "dispatches refused because they were planned against a stale "
    "topology epoch")
_QUARANTINE_TOTAL = obs.REGISTRY.counter(
    "fsm_quarantine_jobs_total",
    "crash-loop quarantine events, by outcome (poisoned = settled as "
    "durable POISON past max_adoptions; refused = admission 409 on a "
    "quarantined uid; released = operator release via "
    "/admin/quarantine)")
_EPOCH.set(0.0)
_ROWS_DEAD.set(0.0)
for _to in (HEALTHY, SUSPECT, DEAD):
    _TRANSITIONS.seed(to=_to)
for _o in ("ok", "failed"):
    _PROBES.seed(outcome=_o)
for _o in ("poisoned", "refused", "released"):
    _QUARANTINE_TOTAL.seed(outcome=_o)


class StaleTopology(RuntimeError):
    """A dispatch (or fused wave) was planned against a topology epoch
    that a row death has since invalidated.  Raised at the dispatch /
    broker entry — BEFORE any device work — so the orchestrator's
    adoption loop rebuilds against the surviving mesh instead of
    launching on dead silicon."""

    def __init__(self, planned: int, current: int):
        self.planned = int(planned)
        self.current = int(current)
        super().__init__(
            f"stale topology epoch: launch planned at epoch {planned} "
            f"but the mesh is at epoch {current} (a partition row died "
            f"in between); re-plan against the surviving topology")


def _device_shaped(exc: BaseException) -> bool:
    """Only DEVICE failures move a row's health — a store blip or a
    cancelled job says nothing about silicon.  Fault-injected trips
    (chaos drills), dispatch-watchdog timeouts, and XLA runtime errors
    (matched by name: jaxlib's class path moves across versions)
    qualify; everything else is ignored."""
    if isinstance(exc, faults.FaultInjected):
        return True
    try:
        from spark_fsm_tpu.utils.watchdog import WatchdogTimeout
        if isinstance(exc, WatchdogTimeout):
            return True
    except Exception:
        pass
    name = type(exc).__name__
    return "XlaRuntimeError" in name or "RuntimeError" == name and (
        "RESOURCE_EXHAUSTED" in str(exc) or "device" in str(exc).lower())


class MeshGuard:
    """Per-partition-row health registry + epoch counter.  One instance
    per process (module singleton via :func:`install`); all state under
    one lock — transitions are rare (a row death is an outage, not a
    hot path) and reads take the lock only on the slow paths."""

    def __init__(self, dead_after: int = 2, probe_every_s: float = 0.0,
                 max_retries: int = 4,
                 clock=time.monotonic) -> None:
        self.dead_after = max(1, int(dead_after))
        self.probe_every_s = float(probe_every_s)
        self.max_retries = max(1, int(max_retries))
        self._clock = clock
        self._lock = threading.Lock()
        self._state: Dict[int, str] = {}
        self._trips: Dict[int, int] = {}
        self._epoch = 0
        # row -> tuple of jax devices, registered by the partitioned
        # orchestrator so the active probe knows what to touch
        self._row_devices: Dict[int, tuple] = {}
        self._next_probe = 0.0

    # -- health state machine ---------------------------------------------

    def state_of(self, row: int) -> str:
        with self._lock:
            return self._state.get(int(row), HEALTHY)

    def dead_rows(self) -> frozenset:
        with self._lock:
            return frozenset(r for r, s in self._state.items() if s == DEAD)

    def note_row_fault(self, row: int, exc: Optional[BaseException] = None
                       ) -> Optional[str]:
        """Record one device-shaped failure against ``row``; returns the
        row's new state.  Non-device exceptions are IGNORED (state
        unchanged, returns None — the caller's signal to re-raise
        rather than retry); callers may pass ``exc=None`` when they
        have already classified the failure as device-shaped."""
        if exc is not None and not _device_shaped(exc):
            return None
        row = int(row)
        with self._lock:
            if self._state.get(row) == DEAD:
                return DEAD
            self._trips[row] = self._trips.get(row, 0) + 1
            if self._trips[row] >= self.dead_after:
                return self._kill_locked(row)
            if self._state.get(row) != SUSPECT:
                self._state[row] = SUSPECT
                _TRANSITIONS.inc(to=SUSPECT)
                log_event("mesh_row_suspect", row=row,
                          trips=self._trips[row])
            return SUSPECT

    def note_row_ok(self, row: int) -> None:
        """A row answered (probe returned, round completed): a suspect
        row heals; a dead row stays dead."""
        row = int(row)
        with self._lock:
            if self._state.get(row) == SUSPECT:
                self._state[row] = HEALTHY
                self._trips[row] = 0
                _TRANSITIONS.inc(to=HEALTHY)
                log_event("mesh_row_healed", row=row)

    def mark_dead(self, row: int) -> str:
        """Operator/peer-driven fence: kill a row unconditionally."""
        with self._lock:
            return self._kill_locked(int(row))

    def _kill_locked(self, row: int) -> str:
        if self._state.get(row) != DEAD:
            self._state[row] = DEAD
            self._epoch += 1
            _TRANSITIONS.inc(to=DEAD)
            _EPOCH.set(float(self._epoch))
            _ROWS_DEAD.set(float(
                sum(1 for s in self._state.values() if s == DEAD)))
            log_event("mesh_row_dead", row=row, epoch=self._epoch)
        return DEAD

    # -- topology epochs ---------------------------------------------------

    def current_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def check_epoch(self, planned: Optional[int]) -> None:
        """Refuse a launch planned against a stale epoch.  ``None``
        passes (the launch predates the plane or partitioning is off)."""
        if planned is None:
            return
        with self._lock:
            current = self._epoch
        if int(planned) != current:
            _STALE_REFUSED.inc()
            raise StaleTopology(int(planned), current)

    # -- active probe ------------------------------------------------------

    def register_rows(self, row_devices: Dict[int, tuple]) -> None:
        """The partitioned orchestrator hands over each row's device
        tuple so :meth:`probe` knows what to touch."""
        with self._lock:
            self._row_devices.update(
                {int(r): tuple(d) for r, d in row_devices.items()})

    def probe(self, rows: Optional[Iterable[int]] = None) -> Dict[int, str]:
        """Zero-width dispatch on each registered (or given) row's own
        devices: a ``device_put`` of an empty array, blocked to
        completion.  Cheap enough to ride the heartbeat — no math, no
        compile — but it exercises the same transfer path a real launch
        does.  Returns row -> resulting state."""
        with self._lock:
            targets = {r: self._row_devices.get(int(r), ())
                       for r in (rows if rows is not None
                                 else list(self._row_devices))}
        out: Dict[int, str] = {}
        for row, devs in targets.items():
            if self.state_of(row) == DEAD:
                out[row] = DEAD
                continue
            try:
                faults.fault_site("device.dispatch", point="probe",
                                  part=f"part{row}")
                if devs:
                    import jax
                    import numpy as np
                    for dev in devs:
                        jax.device_put(np.zeros((0,), np.int32), dev
                                       ).block_until_ready()
                _PROBES.inc(outcome="ok")
                self.note_row_ok(row)
                out[row] = self.state_of(row)
            except Exception as exc:  # noqa: BLE001 — probe failures fence
                _PROBES.inc(outcome="failed")
                st = self.note_row_fault(row, None if _device_shaped(exc)
                                         else exc)
                out[row] = st if st is not None else self.state_of(row)
        return out

    def maybe_probe(self) -> None:
        """Cadenced probe for the lease tick: runs at most every
        ``probe_every_s`` (0 = passive trips only, never probes)."""
        if self.probe_every_s <= 0:
            return
        now = self._clock()
        with self._lock:
            if now < self._next_probe:
                return
            self._next_probe = now + self.probe_every_s
        self.probe()

    # -- fleet agreement (heartbeat payload) -------------------------------

    def heartbeat_payload(self) -> dict:
        with self._lock:
            dead = sorted(r for r, s in self._state.items() if s == DEAD)
            return {"epoch": self._epoch, "dead": dead}

    def merge_peer(self, payload: Optional[dict]) -> None:
        """Adopt a peer's view: dead sets union (a row any replica
        proved dead is dead for everyone), epoch converges to the max —
        monotone in both coordinates, so gossip order cannot matter."""
        if not isinstance(payload, dict):
            return
        try:
            peer_epoch = int(payload.get("epoch", 0))
            peer_dead = [int(r) for r in payload.get("dead", ())]
        except (TypeError, ValueError):
            return
        with self._lock:
            for row in peer_dead:
                if self._state.get(row) != DEAD:
                    self._state[row] = DEAD
                    _TRANSITIONS.inc(to=DEAD)
                    log_event("mesh_row_dead_peer", row=row)
            self._epoch = max(self._epoch, peer_epoch)
            _EPOCH.set(float(self._epoch))
            _ROWS_DEAD.set(float(
                sum(1 for s in self._state.values() if s == DEAD)))

    def stats(self) -> dict:
        with self._lock:
            return {"epoch": self._epoch,
                    "rows": dict(sorted(self._state.items())),
                    "dead_after": self.dead_after,
                    "probe_every_s": self.probe_every_s}


# -- module singleton ------------------------------------------------------

_guard: Optional[MeshGuard] = None


def install(cfg=None, clock=time.monotonic) -> Optional[MeshGuard]:
    """Install the process guard from a MeshguardConfig (None/disabled
    uninstalls — every probe then costs one module-global read)."""
    global _guard
    if cfg is None or not getattr(cfg, "enabled", False):
        _guard = None
        return None
    _guard = MeshGuard(dead_after=getattr(cfg, "dead_after", 2),
                       probe_every_s=getattr(cfg, "probe_every_s", 0.0),
                       max_retries=getattr(cfg, "max_retries", 4),
                       clock=clock)
    return _guard


def get() -> Optional[MeshGuard]:
    return _guard


def reset() -> None:
    """Test hook: drop the singleton (module metrics keep their counts —
    the registry owns those)."""
    global _guard
    _guard = None


# engine-side fast paths: one module-global read when the plane is off

def current_epoch() -> Optional[int]:
    g = _guard
    return None if g is None else g.current_epoch()


def check_epoch(planned: Optional[int]) -> None:
    g = _guard
    if g is not None:
        g.check_epoch(planned)


def note_row_fault(row: Optional[int],
                   exc: Optional[BaseException] = None) -> Optional[str]:
    g = _guard
    if g is None or row is None:
        return None
    return g.note_row_fault(row, exc)


def note_row_ok(row: Optional[int]) -> None:
    g = _guard
    if g is not None and row is not None:
        g.note_row_ok(row)


def note_replan(dead_rows: Iterable[int]) -> None:
    _REPLANS.inc()
    log_event("mesh_replan", dead=sorted(int(r) for r in dead_rows))


# -- crash-loop (poison) quarantine ledger ---------------------------------

def quarantine_key(uid: str) -> str:
    return QUARANTINE_PREFIX + str(uid)


def poison_record(store, uid: str, *, reason: str, adoptions: int,
                  evidence: Optional[list] = None,
                  raw_intent: Optional[str] = None) -> str:
    """Write the durable poison record for ``uid`` (enveloped,
    idempotent: re-settling an already-quarantined uid neither rewrites
    nor recounts).  ``evidence`` is the last holder's trace-spine tail;
    ``raw_intent`` preserves the journal bytes the way integrity
    quarantines do."""
    qkey = quarantine_key(uid)
    if store.peek(qkey) is None:
        rec = json.dumps({
            "key": f"fsm:journal:{uid}", "surface": POISON_SURFACE,
            "uid": str(uid), "ts": round(time.time(), 3),
            "reason": str(reason), "adoptions": int(adoptions),
            "evidence": evidence or [], "value": raw_intent,
        })
        store.set(qkey, envelope.wrap(rec))
        _QUARANTINE_TOTAL.inc(outcome="poisoned")
        log_event("quarantine_poisoned", uid=uid, adoptions=adoptions)
    return qkey


def poisoned(store, uid: str) -> Optional[dict]:
    """The admission gate's peek: the poison record for ``uid``, or
    None.  Integrity quarantines (surface journal/checkpoint/...) do
    NOT block re-admission — only crash-loop poison does."""
    raw = store.peek(quarantine_key(uid))
    if raw is None:
        return None
    payload, verdict = envelope.unwrap(raw)
    if verdict == "corrupt" or payload is None:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    if isinstance(rec, dict) and rec.get("surface") == POISON_SURFACE:
        return rec
    return None


def note_refused(uid: str) -> None:
    _QUARANTINE_TOTAL.inc(outcome="refused")
    log_event("quarantine_refused", uid=uid)


def quarantine_list(store, limit: int = 100) -> List[dict]:
    """The ``/admin/quarantine`` listing: every ``fsm:quarantine:*``
    record (poison AND integrity surfaces — one place to see all
    preserved damage), poison fields surfaced when present."""
    out: List[dict] = []
    for qkey in itertools.islice(store.scan_iter(QUARANTINE_PREFIX),
                                 int(limit)):
        row = {"quarantine_key": qkey}
        payload, verdict = envelope.unwrap(store.peek(qkey))
        if verdict != "corrupt" and payload is not None:
            try:
                rec = json.loads(payload)
                if isinstance(rec, dict):
                    for k in ("uid", "key", "surface", "ts", "reason",
                              "adoptions"):
                        if rec.get(k) is not None:
                            row[k] = rec[k]
            except ValueError:
                pass
        out.append(row)
    return out


def quarantine_release(store, uid: str) -> bool:
    """Operator release: delete the quarantine record so the uid may be
    resubmitted.  Returns False when no record existed (the 404 case)."""
    qkey = quarantine_key(uid)
    if store.peek(qkey) is None:
        return False
    store.delete(qkey)
    _QUARANTINE_TOTAL.inc(outcome="released")
    log_event("quarantine_released", uid=uid)
    return True
