"""Minimal RESP2 (Redis Serialization Protocol) client over stdlib sockets.

The reference persists results/metadata in Redis through a JVM client
(SURVEY.md sec 2 "Redis sink/cache").  This rebuild talks the wire
protocol directly — no third-party client package — which keeps the Redis
seam real and testable in a sandbox with no Redis server: the test suite
runs ``RedisResultStore`` against an in-process RESP server
(tests/test_redis_store.py), and the same bytes reach a production Redis.

Covers what the store needs: command pipelining-free request/response with
simple strings, errors, integers, bulk strings, and arrays.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional, Tuple, Union


class RespError(RuntimeError):
    """Server-side error reply (RESP '-ERR ...')."""


class RespProtocolError(ConnectionError):
    """Malformed/unknown bytes on the reply stream — the connection can no
    longer be trusted to be in sync and must be discarded."""


# Error ELEMENTS inside an array reply surface as RespError values (raising
# mid-array would desync the stream); top-level errors raise.
Reply = Union[None, int, str, RespError, List["Reply"]]


def encode_command(*args: Union[str, bytes, int]) -> bytes:
    """Encode one command as a RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode("utf-8")
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class RespClient:
    """Blocking request/response client; thread-safe via a send lock."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 10.0) -> None:
        self._host, self._port, self._timeout = host, port, timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()
        self._connect()  # fail fast if nothing listens

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        self._buf = b""

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""

    # ---------------------------------------------------------------- io

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:  # payload + trailing \r\n
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        payload, self._buf = self._buf[:n], self._buf[n + 2:]
        return payload

    def _read_reply(self, depth: int = 0) -> Reply:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":  # simple string
            return rest.decode("utf-8")
        if kind == b"-":  # error
            err = RespError(rest.decode("utf-8"))
            if depth:  # an error ELEMENT of an array: the remaining
                return err  # elements must still be consumed — no raise
            raise err
        try:
            if kind == b":":  # integer
                return int(rest)
            if kind == b"$":  # bulk string
                n = int(rest)
                if n == -1:
                    return None
                return self._read_exact(n).decode("utf-8")
            if kind == b"*":  # array
                n = int(rest)
                if n == -1:
                    return None
                return [self._read_reply(depth + 1) for _ in range(n)]
        except ValueError as exc:  # malformed length/integer
            raise RespProtocolError(f"malformed RESP reply {line!r}") from exc
        raise RespProtocolError(f"unknown RESP reply type {line!r}")

    # ------------------------------------------------------------ command

    def command(self, *args: Union[str, bytes, int]) -> Reply:
        with self._lock:
            if self._sock is None:
                self._connect()  # transparent reconnect after a poisoning
            try:
                self._sock.sendall(encode_command(*args))
                return self._read_reply()
            except RespError:
                raise  # server error reply — the stream is still in sync
            except OSError:
                # A timeout/transport/protocol error mid-reply leaves the
                # stream desynced (a late remainder would be parsed as the
                # NEXT command's reply) — drop the connection so the next
                # command starts on a fresh, in-sync socket instead of
                # reading off-by-one replies from this one.
                self.close()
                raise

    # convenience wrappers (the subset the store uses)

    def set(self, key: str, value: str) -> None:
        self.command("SET", key, value)

    def set_px(self, key: str, value: str, px_ms: int,
               nx: bool = False) -> bool:
        """``SET key value PX px_ms [NX]`` — the lease-acquisition
        primitive.  Redis replies +OK on success and Null when NX
        refused the write; True/False respectively."""
        args = ["SET", key, value, "PX", int(px_ms)]
        if nx:
            args.append("NX")
        return self.command(*args) == "OK"

    def pexpire(self, key: str, px_ms: int) -> bool:
        """PEXPIRE — lease heartbeat renewal; False = key gone (lost)."""
        return self.command("PEXPIRE", key, int(px_ms)) == 1

    def pttl(self, key: str) -> int:
        """PTTL in ms; -1 = no expiry, -2 = no such key."""
        reply = self.command("PTTL", key)
        assert isinstance(reply, int)
        return reply

    def get(self, key: str) -> Optional[str]:
        reply = self.command("GET", key)
        assert reply is None or isinstance(reply, str)
        return reply

    def rpush(self, key: str, value: str) -> int:
        reply = self.command("RPUSH", key, value)
        assert isinstance(reply, int)
        return reply

    def lrange(self, key: str, start: int = 0, stop: int = -1) -> List[str]:
        reply = self.command("LRANGE", key, start, stop)
        if reply is None:
            return []
        assert isinstance(reply, list)
        return [r for r in reply if isinstance(r, str)]

    def lpop(self, key: str) -> Optional[str]:
        reply = self.command("LPOP", key)
        assert reply is None or isinstance(reply, str)
        return reply

    def llen(self, key: str) -> int:
        reply = self.command("LLEN", key)
        assert isinstance(reply, int)
        return reply

    def ltrim(self, key: str, start: int, stop: int) -> None:
        self.command("LTRIM", key, start, stop)

    def delete(self, key: str) -> int:
        reply = self.command("DEL", key)
        assert isinstance(reply, int)
        return reply

    def incr(self, key: str) -> int:
        reply = self.command("INCR", key)
        assert isinstance(reply, int)
        return reply

    def keys(self, pattern: str) -> List[str]:
        reply = self.command("KEYS", pattern)
        if reply is None:
            return []
        assert isinstance(reply, list)
        return [r for r in reply if isinstance(r, str)]

    def scan(self, cursor: str = "0", match: Optional[str] = None,
             count: Optional[int] = None) -> "Tuple[str, List[str]]":
        """One SCAN step: ``SCAN cursor [MATCH pat] [COUNT n]`` →
        ``(next_cursor, keys)``.  The cursor is treated as an OPAQUE
        string round-tripped verbatim (real Redis hands back decimal
        bucket cursors, MiniRedis hands back the last key) — "0" starts
        and terminates the iteration in both."""
        args: List[Union[str, bytes, int]] = ["SCAN", cursor]
        if match is not None:
            args += ["MATCH", match]
        if count is not None:
            args += ["COUNT", int(count)]
        reply = self.command(*args)
        assert isinstance(reply, list) and len(reply) == 2, reply
        nxt, batch = reply
        assert isinstance(nxt, str)
        if batch is None:
            batch = []
        assert isinstance(batch, list)
        return nxt, [k for k in batch if isinstance(k, str)]

    def ping(self) -> bool:
        return self.command("PING") == "PONG"
