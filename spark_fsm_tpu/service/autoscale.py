"""Elastic control plane (ISSUE 13) — queue/SLO-driven autoscaling on
the lease substrate.

PR 8/9 built a static-N fleet: leases, stealing, a cluster metrics
plane, per-priority SLO quantiles.  This module closes the loop the
north star ("heavy traffic from millions of users") demands — capacity
follows demand:

- **Leader election**: every replica runs a controller; exactly one
  acts, elected through a short-TTL ``fsm:autoscale:leader`` lease on
  the shared store whose value carries a fencing token from the SAME
  ``fsm:lease:token`` sequence the job leases use — a stale leader's
  decision records are ordered (and ignorable) by token, and a dead
  leader stalls the loop for at most ``leader_ttl_s``.

- **Signals** (read from the heartbeat-cadence peer cache — the
  controller never scans the store): cluster queue depth and free
  capacity from :meth:`LeaseManager.cluster_view`, and the local
  ``/admin/slo`` e2e p99 (the leader's own window; every replica
  observes its own finishes, and under load every replica finishes
  jobs — documented approximation, not a fleet-wide quantile merge).

- **Hysteresis**: a signal becomes a decision only after holding
  continuously for ``hold_s``, and decisions are at least
  ``cooldown_s`` apart — load oscillating inside the band produces
  ZERO decisions (the flap test pins it).

- **Scale-up** publishes a desired-replica-count record
  (``fsm:autoscale:desired``: desired/current/reason/ts/seq/leader) and
  appends it to the ``fsm:autoscale:log`` ring.  The record is a
  REQUEST to the environment: an operator hook, scripts/fleet.py, or a
  k8s controller watches it and boots replicas — the control plane
  decides, the environment supplies (docs/OPERATIONS.md runbook).

- **Scale-down** picks the least-loaded replica (min running+queued,
  draining replicas excluded) and writes a drain DIRECTIVE
  (``fsm:autoscale:drain:{replica}``, short PX so a stale directive
  dies on its own).  The victim's own controller claims the directive
  on its next tick (atomic DEL — exactly one drain per directive) and
  drives :meth:`Miner.drain`: stop admitting → peers steal the queue →
  release leases → exit, the protocol PR 8 already supports.  A
  ``fsm:autoscale:drained:{replica}`` record publishes the drain
  report for the supervisor to reap the process.

Disabled (``[autoscale] enabled = false``, the default) nothing is
built and nothing ticks; the config layer refuses ``autoscale`` without
``[cluster]`` (the lease substrate IS the transport).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional

from spark_fsm_tpu import config
from spark_fsm_tpu.service import obsplane
from spark_fsm_tpu.utils import envelope, obs
from spark_fsm_tpu.utils.obs import log_event


def _open(raw) -> dict:
    """Tolerant verified decode of one autoscale control record:
    envelope unwrap (legacy bare JSON accepted) + json.loads, ``{}``
    for anything rotten.  Control records are re-derived every decide
    cadence, so the degradation posture for corruption is simply a
    skipped epoch — never a crashed control loop (ISSUE 18)."""
    payload, _verdict = envelope.unwrap(raw)
    if payload is None:
        return {}
    try:
        rec = json.loads(payload)
    except ValueError:
        return {}
    return rec if isinstance(rec, dict) else {}

LEADER_KEY = "fsm:autoscale:leader"
DESIRED_KEY = "fsm:autoscale:desired"
LOG_KEY = "fsm:autoscale:log"
LOG_KEEP = 64
_TOKEN_KEY = "fsm:lease:token"  # the lease layer's fencing sequence


def drain_key(replica_id: str) -> str:
    return f"fsm:autoscale:drain:{replica_id}"


def drained_key(replica_id: str) -> str:
    return f"fsm:autoscale:drained:{replica_id}"


_LEADER = obs.REGISTRY.gauge(
    "fsm_autoscale_leader",
    "1 while this replica holds the autoscale leader lease")
_LEADER.set(0)
_DESIRED = obs.REGISTRY.gauge(
    "fsm_autoscale_desired_replicas",
    "the published desired replica count (last decision record; 0 "
    "until a first decision exists)")
_DESIRED.set(0)
_EVALS = obs.REGISTRY.counter(
    "fsm_autoscale_evals_total",
    "controller evaluations while holding the leader lease")
_DECISIONS = (obs.REGISTRY.counter(
    "fsm_autoscale_decisions_total",
    "published scale decisions, by direction")
    .seed(dir="up").seed(dir="down"))
_DIRECTIVES = obs.REGISTRY.counter(
    "fsm_autoscale_drain_directives_total",
    "drain directives claimed and acted on by THIS replica (the "
    "scale-down victim side)")


class Autoscaler:
    """One per replica.  ``decide_every_s=None`` resolves to
    ``leader_ttl_s / 3`` (the lease must be renewed faster than it
    expires); ``0`` means MANUAL ticks (tests).  ``clock`` is the same
    injectable monotonic source the lease layer uses, so the hermetic
    suite drives election, hysteresis and cooldown on a virtual
    clock."""

    def __init__(self, miner, mgr, acfg=None,
                 decide_every_s: Optional[float] = None,
                 clock=time.monotonic,
                 on_drained: Optional[Callable[[dict], None]] = None):
        acfg = acfg if acfg is not None else config.get_config().autoscale
        self.miner = miner
        self.mgr = mgr
        self._store = mgr._store
        self.min_replicas = int(acfg.min_replicas)
        self.max_replicas = int(acfg.max_replicas)
        self.up_queue_per_worker = float(acfg.up_queue_per_worker)
        self.up_p99_s = float(acfg.up_p99_s)
        # predictive scale-up (ISSUE 15 satellite / ROADMAP item 4
        # remainder): EWMA-smoothed fleet admission rate + its
        # derivative, from the heartbeat-piggybacked lifetime "adm"
        # counters; 0 disables the signal entirely
        self.up_rate_derivative = float(acfg.up_rate_derivative)
        self.rate_alpha = float(acfg.rate_alpha)
        self._adm_last: Optional[tuple] = None  # (t, fleet admitted)
        self._rate_ewma: Optional[float] = None
        self._deriv_ewma: Optional[float] = None
        self.down_free_frac = float(acfg.down_free_frac)
        self.hold_s = float(acfg.hold_s)
        self.cooldown_s = float(acfg.cooldown_s)
        self.leader_ttl_s = float(acfg.leader_ttl_s)
        self.drain_timeout_s = float(acfg.drain_timeout_s)
        if decide_every_s is None:
            decide_every_s = (acfg.decide_every_s
                              or self.leader_ttl_s / 3.0)
        self.decide_every_s = float(decide_every_s)
        self._clock = clock
        self.on_drained = on_drained
        self._ttl_ms = max(1, int(self.leader_ttl_s * 1000))
        self._lock = threading.Lock()
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._last_decision_t: Optional[float] = None
        self._last: dict = {}  # last evaluation snapshot (stats())
        self._drain_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def build_for(cls, miner, **kw) -> Optional["Autoscaler"]:
        """The Master's constructor hook: an autoscaler when the boot
        config enables the control plane (requires the miner's lease
        manager — config validation enforces [cluster]), else None."""
        if not config.get_config().autoscale.enabled:
            return None
        if miner._lease is None:
            return None
        return cls(miner, miner._lease, **kw)

    # ----------------------------------------------------------- election

    def _lead(self) -> bool:
        """One election round-trip: NX-acquire the leader lease or
        re-arm it when already ours.  The value carries a token from
        the lease layer's fencing sequence, so any two leader epochs
        are strictly ordered."""
        raw = self._store.peek(LEADER_KEY)
        if raw is not None:
            if _open(raw).get("replica") == self.mgr.replica_id:
                return bool(self._store.pexpire(LEADER_KEY, self._ttl_ms))
            return False
        token = int(self._store.incr(_TOKEN_KEY))
        ok = self._store.set_px(
            LEADER_KEY,
            envelope.wrap(json.dumps(
                {"replica": self.mgr.replica_id, "token": token})),
            self._ttl_ms, nx=True)
        if ok:
            log_event("autoscale_leader_acquired",
                      replica=self.mgr.replica_id, token=token)
        return bool(ok)

    # ------------------------------------------------------------ signals

    def _slo_p99(self) -> Optional[float]:
        """Worst per-priority e2e p99 over the local sliding window
        (None before any job finished here)."""
        try:
            snap = obsplane.slo_snapshot()
        except Exception:
            return None
        worst = None
        for row in snap.get("priorities", {}).values():
            e2e = row.get("e2e") or {}
            if (e2e.get("count") or 0) > 0 and e2e.get("p99") is not None:
                worst = e2e["p99"] if worst is None \
                    else max(worst, e2e["p99"])
        return worst

    @staticmethod
    def _fleet_p99(rows, local: Optional[float]) -> Optional[float]:
        """FLEET-WIDE up_p99 signal (ISSUE 14 satellite): the max over
        the local window and every live replica's heartbeat-piggybacked
        SLO digest — an idle leader is no longer blind while a peer
        saturates.  Digest-less rows (old replicas, empty windows)
        contribute nothing; the merge can only RAISE the signal, never
        mask a hot local window."""
        worst = local
        for r in rows:
            digest = r.get("slo") or {}
            p99 = digest.get("p99")
            if p99 is None or not (digest.get("n") or 0):
                continue
            try:
                p99 = float(p99)
            except (TypeError, ValueError):
                continue
            worst = p99 if worst is None else max(worst, p99)
        return worst

    def _admission_derivative(self, rows, now: float) -> Optional[float]:
        """EWMA of the fleet admission-rate DERIVATIVE (jobs/s per
        second).  Each tick differentiates the fleet's lifetime
        admitted sum against the previous tick, EWMA-smooths the rate,
        then EWMA-smooths the rate's slope — two stages of smoothing
        plus the caller's hold_s window are the hysteresis guard: a
        single bursty tick cannot fake sustained acceleration.  The
        fleet sum steps DOWN when a replica leaves (its lifetime
        counter vanishes with its heartbeat) — a counting artifact,
        not a demand signal, so a negative raw delta RE-BASELINES the
        estimator (fresh warm-up from the new fleet sum) instead of
        feeding a phantom deceleration into the slope, which would
        cancel a pending scale-up exactly when capacity was lost."""
        if self.up_rate_derivative <= 0:
            return None
        adm = sum(int(r.get("adm") or 0) for r in rows)
        last = self._adm_last
        self._adm_last = (now, adm)
        if last is None:
            return None
        dt = now - last[0]
        if dt <= 0:
            return self._deriv_ewma
        if adm < last[1]:
            self._rate_ewma = None
            self._deriv_ewma = None
            return None
        rate = (adm - last[1]) / dt
        a = self.rate_alpha
        prev_rate = self._rate_ewma
        self._rate_ewma = (rate if prev_rate is None
                           else a * rate + (1 - a) * prev_rate)
        if prev_rate is None:
            return None
        deriv = (self._rate_ewma - prev_rate) / dt
        self._deriv_ewma = (deriv if self._deriv_ewma is None
                            else a * deriv + (1 - a) * self._deriv_ewma)
        return self._deriv_ewma

    # ----------------------------------------------------------- decisions

    def _publish(self, direction: str, desired: int, replicas: int,
                 reason: str, victim: Optional[str] = None) -> None:
        token = int(self._store.incr(_TOKEN_KEY))
        rec = {"desired": desired, "replicas": replicas,
               "dir": direction, "reason": reason,
               "victim": victim,
               "leader": self.mgr.replica_id, "seq": token,
               "ts": round(time.time(), 3)}
        payload = envelope.wrap(json.dumps(rec))
        self._store.set(DESIRED_KEY, payload)
        try:
            self._store.rpush(LOG_KEY, payload)
            n = self._store.llen(LOG_KEY)
            while n > LOG_KEEP:
                self._store.lpop(LOG_KEY)
                n -= 1
        except Exception:
            pass  # the log ring is evidence, not control flow
        if victim is not None:
            # short-PX directive: a victim that never claims it (crashed
            # between decision and tick) lets it expire instead of
            # draining a future incarnation out of the blue
            self._store.set_px(
                drain_key(victim), payload,
                max(self._ttl_ms * 4, int(self.drain_timeout_s * 1000)))
        _DESIRED.set(desired)
        _DECISIONS.inc(dir=direction)
        self._last_decision_t = self._clock()
        self._up_since = self._down_since = None
        log_event("autoscale_decision", **rec)

    def _decide(self) -> None:
        view = self.mgr.cluster_view(
            max_age_s=max(self.mgr.heartbeat_s, 0.5))
        rows = view["replicas"]
        live = [r for r in rows if not r.get("draining")]
        replicas = len(live)
        workers = sum(int(r.get("workers") or 0) for r in live)
        queued = sum(int(r.get("queued") or 0) for r in live)
        free = sum(int(r.get("free") or 0) for r in live)
        p99 = self._fleet_p99(live, self._slo_p99())
        load = queued / max(1, workers)
        free_frac = free / max(1, workers)
        deriv = self._admission_derivative(live, self._clock())
        deriv_up = (self.up_rate_derivative > 0 and deriv is not None
                    and deriv >= self.up_rate_derivative)
        up = (load > self.up_queue_per_worker
              or (self.up_p99_s > 0 and p99 is not None
                  and p99 > self.up_p99_s)
              or deriv_up)
        down = (not up and queued == 0
                and free_frac >= self.down_free_frac
                and replicas > self.min_replicas)
        now = self._clock()
        # hysteresis: a signal's clock starts when it first holds and
        # resets the moment it breaks — oscillation inside the band
        # never accumulates hold time, so it never becomes a decision
        # (`is None`, not truthiness: a virtual clock starts at 0.0)
        self._up_since = (now if self._up_since is None
                          else self._up_since) if up else None
        self._down_since = (now if self._down_since is None
                            else self._down_since) if down else None
        in_cooldown = (self._last_decision_t is not None
                       and now - self._last_decision_t < self.cooldown_s)
        with self._lock:
            self._last = {
                "replicas": replicas, "workers": workers,
                "queued": queued, "free": free,
                "load_per_worker": round(load, 3),
                "free_frac": round(free_frac, 3),
                "p99_s": p99, "up": up, "down": down,
                "adm_rate_ewma": (round(self._rate_ewma, 4)
                                  if self._rate_ewma is not None
                                  else None),
                "adm_deriv_ewma": (round(deriv, 5)
                                   if deriv is not None else None),
                # `is not None`: a virtual clock's since-stamp can be
                # 0.0 (same guard as the decision path above)
                "held_up_s": (round(now - self._up_since, 3)
                              if self._up_since is not None else 0.0),
                "held_down_s": (round(now - self._down_since, 3)
                                if self._down_since is not None
                                else 0.0),
                "in_cooldown": in_cooldown}
        if in_cooldown:
            return
        if up and now - self._up_since >= self.hold_s:
            if replicas >= self.max_replicas:
                return
            if load > self.up_queue_per_worker:
                reason = (f"queued/worker {load:.2f} > "
                          f"{self.up_queue_per_worker}")
            elif (self.up_p99_s > 0 and p99 is not None
                  and p99 > self.up_p99_s):
                reason = f"e2e p99 {p99:.2f}s > {self.up_p99_s}s"
            else:
                reason = (f"admission rate accelerating: d(rate)/dt "
                          f"EWMA {deriv:.4f} >= "
                          f"{self.up_rate_derivative} jobs/s^2")
            self._publish("up", replicas + 1, replicas, reason)
            return
        if down and now - self._down_since >= self.hold_s:
            victim = min(
                live,
                key=lambda r: (int(r.get("running") or 0)
                               + int(r.get("queued") or 0),
                               str(r.get("replica") or "")))
            self._publish(
                "down", replicas - 1, replicas,
                f"free capacity {free_frac:.2f} >= "
                f"{self.down_free_frac} with an empty queue",
                victim=str(victim.get("replica") or ""))

    # ----------------------------------------------------- victim (drain)

    def _check_drain_directive(self) -> bool:
        """Claim a drain directive addressed to THIS replica (atomic
        DEL — exactly one drain per directive) and drive the drain on
        its own thread; the controller keeps ticking so the heartbeat/
        lease machinery stays alive through the drain."""
        key = drain_key(self.mgr.replica_id)
        try:
            raw = self._store.peek(key)
            if raw is None:
                return False
            if self._store.delete(key) < 1:
                return False  # raced another claimant (shouldn't exist)
        except Exception as exc:
            log_event("autoscale_directive_check_failed", error=str(exc))
            return False
        rec = _open(raw)
        _DIRECTIVES.inc()
        log_event("autoscale_drain_claimed", replica=self.mgr.replica_id,
                  directive=rec)
        if self._drain_thread is not None and self._drain_thread.is_alive():
            return True

        def _run():
            report = self.miner.drain(
                timeout_s=self.drain_timeout_s,
                reason=rec.get("reason") or "autoscale directive")
            try:
                self._store.set_px(
                    drained_key(self.mgr.replica_id),
                    envelope.wrap(json.dumps(
                        {"report": report,
                         "ts": round(time.time(), 3)})),
                    10 * 60 * 1000)
            except Exception:
                pass
            cb = self.on_drained
            if cb is not None:
                try:
                    cb(report)
                except Exception as exc:
                    log_event("autoscale_on_drained_failed",
                              error=str(exc))

        self._drain_thread = threading.Thread(
            target=_run, daemon=True,
            name=f"fsm-drain-{self.mgr.replica_id[:8]}")
        self._drain_thread.start()
        return True

    # ------------------------------------------------------------- driver

    def tick(self) -> None:
        """One controller step: act on a drain directive addressed to
        us, else run the (leader-gated) evaluation.  Every phase is
        isolated: a store hiccup logs and the thread lives on."""
        try:
            if self._check_drain_directive():
                # a drain victim is no leader: clear the gauge NOW — a
                # drained ex-leader must not export leader=1 next to
                # its successor's 1 for the whole drain window
                _LEADER.set(0)
                return
        except Exception as exc:
            log_event("autoscale_directive_failed", error=str(exc))
        if getattr(self.miner, "draining", False):
            _LEADER.set(0)
            return  # a draining replica evaluates nothing
        try:
            if not self._lead():
                _LEADER.set(0)
                return
            _LEADER.set(1)
            _EVALS.inc()
            self._decide()
        except Exception as exc:
            log_event("autoscale_tick_failed", error=str(exc))

    def _loop(self) -> None:
        while not self._stop.wait(self.decide_every_s):
            self.tick()

    def start(self) -> None:
        if self.decide_every_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fsm-autoscale-{self.mgr.replica_id[:8]}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(max(2.0, 2 * self.decide_every_s))
            self._thread = None
        # drop the leader lease so a successor takes over immediately
        try:
            raw = self._store.peek(LEADER_KEY)
            if raw is not None and _open(raw).get(
                    "replica") == self.mgr.replica_id:
                self._store.delete(LEADER_KEY)
        except Exception:
            pass
        _LEADER.set(0)

    # -------------------------------------------------------------- admin

    def desired(self) -> Optional[dict]:
        try:
            raw = self._store.peek(DESIRED_KEY)
            return (_open(raw) or None) if raw else None
        except Exception:
            return None

    def decision_log(self, n: int = 16) -> List[dict]:
        try:
            rows = self._store.lrange(LOG_KEY)
        except Exception:
            return []
        out = []
        for raw in rows[-n:]:
            rec = _open(raw)
            if rec:
                out.append(rec)
        return out

    def stats(self) -> dict:
        with self._lock:
            last = dict(self._last)
        leader = None
        try:
            raw = self._store.peek(LEADER_KEY)
            leader = _open(raw).get("replica") if raw else None
        except Exception:
            pass
        return {"enabled": True,
                "replica": self.mgr.replica_id,
                "leader": leader,
                "is_leader": leader == self.mgr.replica_id,
                "draining": bool(getattr(self.miner, "draining", False)),
                "bounds": [self.min_replicas, self.max_replicas],
                "up_queue_per_worker": self.up_queue_per_worker,
                "up_p99_s": self.up_p99_s,
                "down_free_frac": self.down_free_frac,
                "hold_s": self.hold_s, "cooldown_s": self.cooldown_s,
                "decide_every_s": self.decide_every_s,
                "last_eval": last,
                "desired": self.desired(),
                "decisions": self.decision_log()}


def build_for(miner, **kw) -> Optional[Autoscaler]:
    return Autoscaler.build_for(miner, **kw)
