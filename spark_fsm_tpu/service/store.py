"""Result/metadata store — the reference's RedisSink/RedisCache contract.

The reference persists mined patterns/rules, job statuses, registered
field specs, and tracked events in Redis (SURVEY.md sec 1 L1, sec 5
checkpoint row: "the model IS the mined pattern/rule set persisted once at
job end").  This module provides the same contract behind an interface
with two implementations:

- ``ResultStore``: in-process, thread-safe dict store (the default — no
  external service needed, mirrors Redis key semantics).
- ``RedisResultStore``: the same contract over a real Redis server,
  speaking RESP2 directly via service/resp.py (no client package);
  selected with ``store.backend = "redis"`` in the boot config.

Key layout follows the reference's convention: ``fsm:status:<uid>``,
``fsm:pattern:<uid>``, ``fsm:rule:<uid>``, ``fsm:fields:<topic>``,
``fsm:track:<topic>``.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_fsm_tpu.utils import envelope, faults, obs

# Latency of the three guarded store verbs, labelled by op and backend
# (inproc latencies are the no-op baseline a Redis deployment's numbers
# are read against).  Sub-ms buckets dominate; the shared ladder keeps
# cross-metric comparisons on one set of edges.
_STORE_OP_SECONDS = obs.REGISTRY.histogram(
    "fsm_store_op_seconds", "result-store I/O verb latency")


class _timed:
    """Tiny context manager: observe the verb's wall into the shared
    histogram even when the verb raises (a slow FAILING store is the
    case the scrape most needs to show)."""

    __slots__ = ("op", "backend", "t0")

    def __init__(self, op: str, backend: str):
        self.op = op
        self.backend = backend

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        _STORE_OP_SECONDS.observe(time.monotonic() - self.t0,
                                  op=self.op, backend=self.backend)


class ResultStore:
    """Thread-safe in-process store with Redis-like key semantics.

    ``clock`` (default ``time.monotonic``) drives key EXPIRY — the lease
    layer's substrate (service/lease.py).  Injectable so lease tests run
    hermetically against a virtual clock instead of sleeping out TTLs.
    Expiry is lazy (Redis-style): an expired key is purged the next time
    any verb touches it or a ``keys`` scan walks past it.
    """

    def __init__(self, clock=None) -> None:
        self._lock = threading.RLock()
        self._kv: Dict[str, str] = {}
        self._lists: Dict[str, List[str]] = {}
        self._expiry: Dict[str, float] = {}  # key -> clock() deadline
        self._clock = clock if clock is not None else time.monotonic

    def _alive(self, key: str) -> bool:
        """Purge ``key`` if its TTL lapsed; True while it (still) lives.
        Callers hold ``self._lock``."""
        deadline = self._expiry.get(key)
        if deadline is not None and self._clock() >= deadline:
            self._expiry.pop(key, None)
            self._kv.pop(key, None)
            self._lists.pop(key, None)
            return False
        return key in self._kv or key in self._lists

    # -- generic ops (Redis GET/SET/RPUSH/LRANGE equivalents) --------------
    # The three primary I/O verbs carry fault-site guards (utils/faults):
    # the guard raises BEFORE the mutation, so an injected failure models
    # an I/O error with nothing applied — the retry policies layered on
    # top (StoreCheckpoint) re-run the whole verb safely.

    def set(self, key: str, value: str) -> None:
        with _timed("set", "inproc"):
            faults.fault_site("store.set", key=key)
            with self._lock:
                # Redis SET semantics: a plain SET clears any TTL
                self._expiry.pop(key, None)
                self._kv[key] = value

    def get(self, key: str) -> Optional[str]:
        with _timed("get", "inproc"):
            faults.fault_site("store.get", key=key)
            with self._lock:
                self._alive(key)
                value = self._kv.get(key)
            # bitrot chaos seam (ISSUE 18): disarmed = one global read
            return faults.corrupt_value("store.corrupt", value, key=key)

    def peek(self, key: str) -> Optional[str]:
        """Guard-free read for scrape-time metric collectors AND the
        lease layer: skips the fault-injection site AND the latency
        histogram, so a /metrics scrape can never advance (or consume)
        an armed ``store.get`` trigger mid-chaos-drill, collector reads
        don't pollute the I/O latency distribution, and lease
        verification carries its OWN fault sites (``lease.*``) instead
        of riding the store's."""
        with self._lock:
            self._alive(key)
            return self._kv.get(key)

    # -- key expiry (the lease layer's substrate) --------------------------
    # Mirrors the Redis verbs the lease protocol needs: atomic
    # SET..PX[..NX] for acquisition, PEXPIRE for heartbeat renewal, PTTL
    # for observation.  Deliberately NOT guarded by the store.* fault
    # sites — service/lease.py wraps these in its own ``lease.acquire``/
    # ``lease.renew``/``lease.steal`` sites so chaos drills target the
    # lease protocol without collateral damage to unrelated store drills.

    def set_px(self, key: str, value: str, px_ms: int,
               nx: bool = False) -> bool:
        """Redis ``SET key value PX px_ms [NX]``: write with a TTL;
        with ``nx`` only when the key does not (or no longer) exists.
        Returns False when NX refused the write."""
        with self._lock:
            if nx and self._alive(key):
                return False
            self._kv[key] = value
            self._expiry[key] = self._clock() + px_ms / 1000.0
            return True

    def pexpire(self, key: str, px_ms: int) -> bool:
        """Redis PEXPIRE: re-arm a live key's TTL; False if the key is
        missing/expired (the lease-renewal race signal)."""
        with self._lock:
            if not self._alive(key):
                return False
            self._expiry[key] = self._clock() + px_ms / 1000.0
            return True

    def pttl(self, key: str) -> int:
        """Redis PTTL: remaining TTL in ms; -1 = no expiry, -2 = no key."""
        with self._lock:
            if not self._alive(key):
                return -2
            deadline = self._expiry.get(key)
            if deadline is None:
                return -1
            return max(0, int((deadline - self._clock()) * 1000))

    def rpush(self, key: str, value: str) -> None:
        with _timed("rpush", "inproc"):
            faults.fault_site("store.rpush", key=key)
            with self._lock:
                self._lists.setdefault(key, []).append(value)

    def lrange(self, key: str) -> List[str]:
        with self._lock:
            values = list(self._lists.get(key, []))
        # per-ELEMENT bitrot seam: nth addresses a specific chunk
        return faults.corrupt_list("store.corrupt", values, key=key)

    def lpop(self, key: str) -> Optional[str]:
        with self._lock:
            lst = self._lists.get(key)
            return lst.pop(0) if lst else None

    def llen(self, key: str) -> int:
        with self._lock:
            return len(self._lists.get(key, ()))

    def ltrim(self, key: str, keep: int) -> None:
        """Keep only the FIRST ``keep`` entries of a list (Redis LTRIM
        key 0 keep-1) — the checkpoint torn-tail heal primitive."""
        with self._lock:
            lst = self._lists.get(key)
            if lst is not None:
                del lst[max(0, keep):]

    def delete(self, key: str) -> int:
        """Redis DEL: returns how many keys were removed (0 or 1) — the
        atomic ownership arbiter the work-stealing claim rides on
        (exactly ONE caller ever observes 1 for a given live key)."""
        with self._lock:
            alive = self._alive(key)
            self._expiry.pop(key, None)
            self._kv.pop(key, None)
            self._lists.pop(key, None)
            return 1 if alive else 0

    def incr(self, key: str) -> int:
        """Redis INCR: atomic counter (service metrics and the lease
        fencing-token sequence live on these)."""
        with self._lock:
            self._alive(key)
            value = int(self._kv.get(key, "0")) + 1
            self._kv[key] = str(value)
            return value

    def clear_job(self, uid: str, *, keep_status_log: bool = False,
                  keep_frontier: bool = False) -> None:
        """Remove a job's error/results (and optionally its status log) so a
        reused uid reports THIS job, not a predecessor's leftovers.
        ``keep_frontier`` preserves the checkpoint keys: a checkpointed
        resubmit (the restart-recovery path) must resume from the
        persisted frontier, not wipe it — the engine's fingerprint check
        still discards a frontier that doesn't match the new data."""
        keys = [f"fsm:error:{uid}", f"fsm:pattern:{uid}", f"fsm:rule:{uid}",
                f"fsm:stats:{uid}"]
        if not keep_frontier:
            keys += [f"fsm:frontier:{uid}", f"fsm:frontier:results:{uid}"]
        if not keep_status_log:
            keys.append(f"fsm:status:log:{uid}")
        for key in keys:
            self.delete(key)

    def keys(self, prefix: str) -> List[str]:
        """Keys (kv + list) starting with ``prefix``.  The Redis backend
        maps this to KEYS, which blocks the server while it scans — the
        recurring walks (heartbeat peers, steal scan, journal recovery)
        use :meth:`scan_iter` instead; this stays for tests and one-off
        admin reads."""
        with self._lock:
            return sorted({k for k in list(self._kv) + list(self._lists)
                           if k.startswith(prefix) and self._alive(k)})

    # -- cursor-based key scan (Redis SCAN) --------------------------------
    # The lease layer's steal/heartbeat/recovery walks repeat on every
    # heartbeat tick; at thousands of replicas sharing one store a KEYS
    # walk per tick would serialize the server on each scan (the ROADMAP
    # item 1 follow-up).  SCAN iterates in bounded batches.  Cursors are
    # OPAQUE strings (exactly the Redis contract): "0" starts AND ends an
    # iteration; any other value is backend-defined.  The in-process
    # backend (and MiniRedis) use the last key returned, so keys alive
    # for the whole iteration are seen exactly once; real Redis may
    # return duplicates across rehashes — every caller here is
    # idempotent per key (peer parse, atomic DEL claim, journal heal).

    def scan_keys(self, prefix: str, cursor: str = "0",
                  count: int = 512) -> Tuple[str, List[str]]:
        """One SCAN step: up to ``count`` live keys with ``prefix``
        after ``cursor``; returns ``(next_cursor, keys)`` with
        next_cursor == "0" when the iteration is complete."""
        with self._lock:
            keys = sorted({k for k in list(self._kv) + list(self._lists)
                           if k.startswith(prefix) and self._alive(k)})
        if cursor != "0":
            keys = keys[bisect.bisect_right(keys, cursor):]
        batch = keys[:max(1, int(count))]
        nxt = "0" if len(keys) <= len(batch) else batch[-1]
        return nxt, batch

    def scan_iter(self, prefix: str, count: int = 512):
        """Generator over :meth:`scan_keys` — the one spelling every
        recurring walk uses (lease peers/steal, journal recovery)."""
        cursor = "0"
        while True:
            cursor, batch = self.scan_keys(prefix, cursor, count)
            for key in batch:
                yield key
            if cursor == "0":
                return

    def probe(self) -> bool:
        """Active health probe (service/storeguard.py): can the store be
        reached RIGHT NOW?  The in-process store is reachable by
        construction — outages against it are simulated by wrapping
        (tests) or by the ``storeguard.probe`` fault site, which the
        guard weaves around this call."""
        return True

    # -- write-ahead job journal -------------------------------------------
    # One intent record per live train job (``fsm:journal:{uid}``),
    # written at submit and cleared on every terminal status.  A record
    # that survives a process death marks an ORPHAN: the boot recovery
    # pass (service/actors.recover_orphans) resubmits checkpointed
    # orphans (they resume from their persisted frontier) and gives the
    # rest a durable "interrupted by restart" failure, so no client ever
    # polls a forever-pending uid.

    def journal_set(self, uid: str, payload_json: str) -> None:
        faults.fault_site("service.journal", key=f"fsm:journal:{uid}")
        # every journal intent is written enveloped (utils/envelope.py);
        # journal_get verifies, and legacy pre-envelope intents pass
        # through untouched until their next write upgrades them
        self.set(f"fsm:journal:{uid}", envelope.wrap(payload_json))

    def journal_get(self, uid: str) -> Optional[str]:
        """Verified journal read: the intent payload on an intact or
        legacy value; on a CORRUPT envelope the raw damaged bytes are
        returned so the caller's JSON parse fails into its existing
        degrade path (recover_orphans quarantines, lease._parse treats
        it as not-ours) instead of this layer guessing a policy."""
        raw = self.get(f"fsm:journal:{uid}")
        payload, verdict = envelope.unwrap(raw)
        if verdict == "missing":
            return None
        # lazy import: integrity sits above the store in the service
        # layering (it holds the counters + quarantine policy)
        from spark_fsm_tpu.service import integrity
        integrity.note_read("journal", verdict)
        return raw if verdict == "corrupt" else payload

    def journal_clear(self, uid: str) -> None:
        self.delete(f"fsm:journal:{uid}")

    def journal_uids(self) -> List[str]:
        # cursor-based: the recovery pass runs on every heartbeat tick
        # in cluster mode, not just at boot — a KEYS walk here would
        # block the shared server once per replica per tick
        return [k[len("fsm:journal:"):]
                for k in self.scan_iter("fsm:journal:")]

    # -- durable trace spine (service/obsplane.py) -------------------------
    # Append-only list of span-chunk JSON per job.  Deliberately
    # guard-free (like ``peek``): spine writes are observability riding
    # the job's threads — an armed ``store.rpush`` chaos drill targets
    # checkpoint deltas, and trace flushes consuming its trigger counts
    # would make pinned-seed drills nondeterministic.  Fencing lives a
    # layer up (obsplane.TraceSpine), not in the store verb.

    def spine_append(self, uid: str, chunk_json: str) -> None:
        with self._lock:
            self._lists.setdefault(f"fsm:trace:{uid}", []).append(chunk_json)

    def spine_chunks(self, uid: str) -> List[str]:
        with self._lock:
            values = list(self._lists.get(f"fsm:trace:{uid}", ()))
        # raise-free but NOT bitrot-free: the spine is a durable surface
        # too, and obsplane's verified reader must see planted damage
        return faults.corrupt_list("store.corrupt", values,
                                   key=f"fsm:trace:{uid}")

    def spine_trim(self, uid: str, keep_last: int) -> None:
        """Retention bound: keep only the NEWEST ``keep_last`` chunks
        (the opposite end from ltrim — old warmup chunks are the ones a
        straggler hunt can spare)."""
        with self._lock:
            lst = self._lists.get(f"fsm:trace:{uid}")
            if lst is not None and len(lst) > max(0, keep_last):
                del lst[:len(lst) - max(0, keep_last)]

    # -- job status registry (RedisCache.addStatus / status) ---------------

    def add_status(self, uid: str, status: str) -> None:
        ts = int(time.time() * 1000)
        self.set(f"fsm:status:{uid}", status)
        self.rpush(f"fsm:status:log:{uid}", f"{ts}:{status}")

    def status(self, uid: str) -> Optional[str]:
        return self.get(f"fsm:status:{uid}")

    def status_log(self, uid: str) -> List[Tuple[int, str]]:
        out = []
        for entry in self.lrange(f"fsm:status:log:{uid}"):
            ts, _, st = entry.partition(":")
            out.append((int(ts), st))
        return out

    # -- mined results (RedisSink.addPatterns / addRules) ------------------

    def add_patterns(self, uid: str, payload_json: str) -> None:
        self.set(f"fsm:pattern:{uid}", payload_json)

    def patterns(self, uid: str) -> Optional[str]:
        return self.get(f"fsm:pattern:{uid}")

    def add_rules(self, uid: str, payload_json: str) -> None:
        self.set(f"fsm:rule:{uid}", payload_json)

    def rules(self, uid: str) -> Optional[str]:
        return self.get(f"fsm:rule:{uid}")

    # -- field specs (FSMRegistrar / spec.Fields) --------------------------

    def add_fields(self, topic: str, spec_json: str) -> None:
        self.set(f"fsm:fields:{topic}", spec_json)

    def fields(self, topic: str) -> Optional[str]:
        return self.get(f"fsm:fields:{topic}")

    # -- tracked events (FSMTracker ingest) --------------------------------

    def track(self, topic: str, event_json: str) -> None:
        self.rpush(f"fsm:track:{topic}", event_json)

    def tracked(self, topic: str) -> List[str]:
        return self.lrange(f"fsm:track:{topic}")


class RedisResultStore(ResultStore):
    """Store over a real Redis — the reference's RedisSink/RedisCache pair
    (SURVEY.md sec 2), speaking RESP2 directly via service/resp.py (no
    client package needed).  Same key layout as the in-process store, so
    the two are interchangeable behind ``store.backend`` in the boot
    config; protocol-tested against an in-process RESP server in
    tests/test_redis_store.py.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout_s: float = 10.0) -> None:
        super().__init__()
        from spark_fsm_tpu.service.resp import RespClient

        self._host, self._port = host, port
        self._timeout_s = float(timeout_s)
        self._r = RespClient(host=host, port=port, timeout=self._timeout_s)
        self._r.ping()  # fail fast at boot, not on first job
        # the probe rides a DEDICATED lazily-built connection with a
        # short timeout: a data connection wedged in a blackhole must
        # not alias onto the health verdict, and a probe against a
        # down store must answer in ~a second, not the data timeout
        self._probe_client = None

    def set(self, key: str, value: str) -> None:
        with _timed("set", "redis"):
            faults.fault_site("store.set", key=key)
            self._r.set(key, value)

    def get(self, key: str) -> Optional[str]:
        with _timed("get", "redis"):
            faults.fault_site("store.get", key=key)
            return faults.corrupt_value("store.corrupt", self._r.get(key),
                                        key=key)

    def peek(self, key: str) -> Optional[str]:
        return self._r.get(key)

    def set_px(self, key: str, value: str, px_ms: int,
               nx: bool = False) -> bool:
        return self._r.set_px(key, value, px_ms, nx=nx)

    def pexpire(self, key: str, px_ms: int) -> bool:
        return self._r.pexpire(key, px_ms)

    def pttl(self, key: str) -> int:
        return self._r.pttl(key)

    def rpush(self, key: str, value: str) -> None:
        with _timed("rpush", "redis"):
            faults.fault_site("store.rpush", key=key)
            self._r.rpush(key, value)

    def lrange(self, key: str) -> List[str]:
        return faults.corrupt_list("store.corrupt",
                                   self._r.lrange(key, 0, -1), key=key)

    def lpop(self, key: str) -> Optional[str]:
        return self._r.lpop(key)

    def llen(self, key: str) -> int:
        return self._r.llen(key)

    def ltrim(self, key: str, keep: int) -> None:
        if keep <= 0:
            self._r.delete(key)
        else:
            self._r.ltrim(key, 0, keep - 1)

    def delete(self, key: str) -> int:
        return self._r.delete(key)

    def incr(self, key: str) -> int:
        return self._r.incr(key)

    def keys(self, prefix: str) -> List[str]:
        # Redis KEYS is O(keyspace) and blocks the server — kept for
        # tests/one-off admin reads only; every recurring walk goes
        # through scan_keys/scan_iter below.
        return sorted(self._r.keys(prefix + "*"))

    def scan_keys(self, prefix: str, cursor: str = "0",
                  count: int = 512) -> Tuple[str, List[str]]:
        nxt, batch = self._r.scan(cursor, match=prefix + "*", count=count)
        # MATCH already filters server-side; re-filter defensively so a
        # backend returning unmatched keys cannot leak them upward
        return nxt, [k for k in batch if k.startswith(prefix)]

    def probe(self) -> bool:
        """One PING on the dedicated probe connection (built fresh after
        any failure, so a dead socket never caches a stale verdict).
        Raises the transport error on an unreachable store — the
        guard's state machine classifies it."""
        from spark_fsm_tpu.service.resp import RespClient

        try:
            if self._probe_client is None:
                self._probe_client = RespClient(
                    host=self._host, port=self._port,
                    timeout=min(2.0, self._timeout_s))
            return self._probe_client.ping()
        except Exception:
            # drop the probe connection: the next probe reconnects from
            # scratch instead of reading a desynced stream
            try:
                if self._probe_client is not None:
                    self._probe_client.close()
            finally:
                self._probe_client = None
            raise

    def spine_append(self, uid: str, chunk_json: str) -> None:
        self._r.rpush(f"fsm:trace:{uid}", chunk_json)

    def spine_chunks(self, uid: str) -> List[str]:
        return faults.corrupt_list(
            "store.corrupt", self._r.lrange(f"fsm:trace:{uid}", 0, -1),
            key=f"fsm:trace:{uid}")

    def spine_trim(self, uid: str, keep_last: int) -> None:
        if keep_last <= 0:
            self._r.delete(f"fsm:trace:{uid}")
        else:  # LTRIM key -N -1: keep the newest N entries
            self._r.ltrim(f"fsm:trace:{uid}", -keep_last, -1)
