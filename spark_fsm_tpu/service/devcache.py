"""Device-store cache for repeat ``/train`` mines (Spark's cached-RDD
analog, SURVEY.md sec 2.2).

Every ``/train`` used to rebuild the vertical DB's device store from
scratch: token upload over the host link plus the HBM scatter-build —
~0.3 s of fixed cost per mine on a tunneled TPU (BENCH_SUITE config-1
note), paid even when the client re-mines the exact same data at the
same support (the reference's explore/track->mine loop).  This cache
keeps the constructed ENGINE — device store, Pallas launchers, compiled
programs — keyed by a CONTENT fingerprint of the sequence data plus
every parameter that shapes the engine, so a repeat mine skips the
upload, the scatter-build, and engine construction entirely.

Correctness by construction:

- the fingerprint hashes the flattened token representation (the exact
  arrays the vertical build consumes), so any data change — including a
  ``/track`` write feeding a TRACKED source — changes the key and
  misses; no explicit invalidation hook can be forgotten;
- entries are checked out EXCLUSIVELY for the duration of a mine (the
  engines' device stores are mutable scratch); a concurrent identical
  request simply builds its own engine (counted as a busy miss);
- eviction is LRU under an HBM budget — dropping an entry only drops
  the reference, the device memory frees when the arrays do.

Scope: the plain SPADE_TPU path (queue or classic engine — the two that
keep their store across ``mine()`` calls) via :class:`SpadeEngineCache`
— INCLUDING checkpointed jobs (the cached engine holds only the
immutable store + compiled programs; frontier state arrives per call
from the checkpoint snapshot, whose engine fingerprint is validated
against the checked-out engine before resuming); the constrained cSPADE
path via :class:`CSpadeEngineCache` (the max-start engine keeps its
item store and state pool across ``mine()`` calls exactly like the
classic engine — its fingerprint folds in maxgap/maxwindow, which
select different compiled kernels AND different enumerations); and
TSR_TPU via :class:`TsrEngineCache` (host-side reuse — see its
docstring).  Stream pushes stay uncached (a sliding window's data
changes every push, so every push would insert a dead entry).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional

import numpy as np

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.utils import faults, jobctl, obs
from spark_fsm_tpu.utils.canonical import PatternResult
from spark_fsm_tpu.utils.obs import log_event
from spark_fsm_tpu.utils.retry import CircuitBreaker


def db_fingerprint(db: SequenceDB) -> str:
    """Content hash of the flattened token representation — two DBs with
    equal flattenings are identical inputs to the vertical build."""
    from spark_fsm_tpu.data import fasttok

    ft = fasttok.flatten(db)
    if ft is None:
        ft = fasttok.flatten_numpy(db)
    seq_lengths, counts, raw_items = ft
    h = hashlib.blake2b(digest_size=16)
    h.update(np.int64(len(db)).tobytes())
    for arr in (seq_lengths, counts, raw_items):
        a = np.ascontiguousarray(arr)
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class _Entry:
    __slots__ = ("engine", "nbytes", "busy")

    def __init__(self, engine, nbytes: int):
        self.engine = engine
        self.nbytes = nbytes
        self.busy = False


class _EngineCacheBase:
    """The concurrency-sensitive scaffolding both engine caches share:
    lock + LRU OrderedDict + exclusive busy-flag checkout + insert that
    never displaces a checked-out entry.  Subclasses supply only the
    eviction policy (``_evict_locked``) and the engine-build bodies —
    one copy of the checkout/release/insert logic means a race fixed
    here is fixed for both caches."""

    # device-put circuit breaker: this many CONSECUTIVE failures of the
    # cached device route open it (all mines take the uncached host-path
    # wrapper), and after the cooldown ONE probe mine re-tries the cache
    # (half-open) — success closes it, failure re-opens for another
    # cooldown.  /admin/health surfaces each cache's breaker snapshot.
    BREAKER_THRESHOLD = 3
    BREAKER_COOLDOWN_S = 30.0

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.stats = {"hits": 0, "misses": 0, "busy_misses": 0,
                      "evictions": 0, "breaker_fallbacks": 0}
        self.breaker = CircuitBreaker(type(self).__name__,
                                      threshold=self.BREAKER_THRESHOLD,
                                      cooldown_s=self.BREAKER_COOLDOWN_S)

    def _mine_guarded(self, cached_fn, fallback_fn):
        """Run the cached device route behind the circuit breaker.

        A failure ANYWHERE in the cached route (fingerprint + checkout +
        device build/insert — the ``devcache.put`` fault site guards its
        entry) counts against the breaker and PROPAGATES: job-level
        supervision (the Miner's retry) owns re-running it, exactly as
        for an uncached mine — swallowing the error here would also
        swallow deliberate aborts (a crashing checkpoint callback) and
        double the device work on every real engine failure.  Once
        ``BREAKER_THRESHOLD`` consecutive failures open the breaker,
        every call takes ``fallback_fn`` — the plain uncached host-path
        wrapper — outright, paying no device-put cost on a failing
        cache layer, until the post-cooldown half-open probe closes it
        again."""
        if not self.breaker.allow():
            with self._lock:
                self.stats["breaker_fallbacks"] += 1
            obs.trace_event("devcache_breaker_fallback",
                            cache=type(self).__name__)
            return fallback_fn()
        try:
            faults.fault_site("devcache.put", cache=type(self).__name__)
            res = cached_fn()
        except ValueError:
            # deterministic request/validation errors (the Miner's own
            # no-retry class): re-running them cannot succeed and they
            # say nothing about the cache's device seam — one bad job
            # must not open the breaker for healthy traffic
            raise
        except jobctl.JobAborted:
            # deadline/cancel aborts are CLIENT outcomes, not device
            # failures: a batch of operator cancels (or deadline
            # expiries under overload — the exact scenario the
            # admission layer exists for) must not open the breaker
            # and push healthy mines onto the uncached host path
            raise
        except Exception as exc:
            self.breaker.failure()
            log_event("devcache_fault", cache=type(self).__name__,
                      error=f"{type(exc).__name__}: {exc}")
            raise
        self.breaker.success()
        return res

    def _checkout(self, key) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and not e.busy:
                e.busy = True
                self._entries.move_to_end(key)
                self.stats["hits"] += 1
                kind = "hit"
            else:
                kind = "busy_miss" if e is not None else "miss"
                self.stats["busy_misses" if e is not None else "misses"] += 1
                e = None
        obs.trace_event("devcache_" + kind, cache=type(self).__name__)
        return e

    def _mine_checked_out(self, entry: _Entry, runner=None):
        """Run a checked-out engine's mine: zero the accumulated numeric
        stats (engines carry lifetime totals across mine() calls), run,
        and SNAPSHOT the stats dict BEFORE releasing the busy flag — a
        concurrent checkout zeroes the same dict the moment busy drops,
        so reading ``engine.stats`` after release races.  ``runner``
        overrides the default ``engine.mine()`` call (the checkpointed
        path resumes from a snapshot).  Returns
        ``(result, stats_snapshot)``."""
        eng = entry.engine
        for k, v in eng.stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                eng.stats[k] = 0
        try:
            res = eng.mine() if runner is None else runner(eng)
            snap = dict(eng.stats)
            return res, snap
        finally:
            # scrub on EVERY exit (a raising mine may have left transient
            # device state too), and always before the busy release
            try:
                self._scrub(eng)
            finally:
                with self._lock:
                    entry.busy = False

    def _scrub(self, engine) -> None:
        """Drop transient device state a mine may have left on the
        engine before it goes back on the shelf (called while the entry
        is still exclusively checked out).  Base: nothing to drop."""

    def _insert(self, key, engine, nbytes: int) -> None:
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old.busy:
                # a busy-miss rebuild racing the checked-out entry: keep
                # the in-use one (replacing it would transiently hold
                # two engines' working sets); this engine stays uncached
                return
            self._entries[key] = _Entry(engine, nbytes)
            self._entries.move_to_end(key)
            self._evict_locked(key)

    def _evict_locked(self, new_key) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


class _HbmBudgetCache(_EngineCacheBase):
    """Byte-budgeted LRU shared by the device-store caches (plain SPADE
    and cSPADE): entries are charged their engine's persistent HBM
    working set and LRU-evicted under a fraction of device memory.

    ``_BUDGET_FRACTION`` is per-CLASS and the module-level cache
    instances' fractions must SUM to a figure that coexists with a live
    queue-engine working set (~45% of HBM, QueueCaps.for_budget) plus
    kernel temps: plain 25% + cSPADE 12.5% = 37.5% pinned worst-case.
    A subclass raising its fraction must re-do that arithmetic."""

    _BUDGET_FRACTION = 0.25

    def __init__(self, budget_bytes: Optional[int] = None):
        super().__init__()
        self._budget = budget_bytes

    def _budget_bytes(self) -> int:
        if self._budget is not None:
            return self._budget
        import jax

        from spark_fsm_tpu.models._common import device_hbm_budget

        return int(self._BUDGET_FRACTION
                   * device_hbm_budget(jax.devices()[0]))

    def _engine_bytes(self, engine) -> int:
        if hasattr(engine, "nbytes"):
            return int(engine.nbytes())
        rows = engine.store.shape[0]
        return rows * engine.n_seq * engine.n_words * 4

    def _insert_engine(self, key, engine) -> None:
        nbytes = self._engine_bytes(engine)
        if nbytes > self._budget_bytes():
            return  # a store bigger than the whole budget never caches
        self._insert(key, engine, nbytes)

    def _evict_locked(self, new_key) -> None:
        budget = self._budget_bytes()
        total = sum(e.nbytes for e in self._entries.values())
        for k in list(self._entries):
            if total <= budget:
                break
            e = self._entries[k]
            if e.busy or k == new_key:
                continue
            total -= e.nbytes
            del self._entries[k]
            self.stats["evictions"] += 1


class SpadeEngineCache(_HbmBudgetCache):
    """LRU engine cache with exclusive checkout; see module docstring."""

    def mine(self, db: SequenceDB, minsup_abs: int, *,
             mesh=None, stats_out: Optional[dict] = None,
             max_pattern_itemsets: Optional[int] = None,
             shape_buckets: bool = False,
             fused: str = "auto",
             checkpoint=None,
             **kwargs) -> List[PatternResult]:
        """Cached equivalent of ``mine_spade_tpu`` for the plain path.

        Modes without a store-keeping engine ("never"/"dense" pins, or
        explicit engine kwargs the cache does not key) fall through to
        the uncached wrapper.

        ``checkpoint`` (the load/save/every_s contract): a checkpointed
        job rides the SAME data-keyed entries as plain mines — the
        cached engine holds only the immutable store + compiled
        programs, never frontier state, so a resume simply seeds the
        checked-out engine from the snapshot.  Snapshot identity is
        enforced where it must be: ``load_checkpoint`` validates the
        frontier fingerprint (data + minsup + parameters) against the
        checked-out engine before resuming, so a stale snapshot
        restarts fresh instead of garbling.
        """
        from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

        def fallback():
            return mine_spade_tpu(
                db, minsup_abs, mesh=mesh, stats_out=stats_out,
                max_pattern_itemsets=max_pattern_itemsets,
                shape_buckets=shape_buckets, fused=fused,
                checkpoint=checkpoint, **kwargs)

        if fused not in ("auto", "queue") or kwargs:
            return fallback()
        return self._mine_guarded(
            lambda: self._mine_cached(
                db, minsup_abs, mesh=mesh, stats_out=stats_out,
                max_pattern_itemsets=max_pattern_itemsets,
                shape_buckets=shape_buckets, fused=fused,
                checkpoint=checkpoint),
            fallback)

    def _mine_cached(self, db, minsup_abs, *, mesh, stats_out,
                     max_pattern_itemsets, shape_buckets, fused,
                     checkpoint):
        key = (db_fingerprint(db), int(minsup_abs), mesh,
               max_pattern_itemsets, bool(shape_buckets), fused)
        entry = self._checkout(key)
        if entry is not None:
            runner = None
            if checkpoint is not None:
                from spark_fsm_tpu.models._common import load_checkpoint

                def runner(eng):
                    resume, save_cb, every_s = load_checkpoint(
                        checkpoint, eng.frontier_fingerprint())
                    return eng.mine(resume=resume, checkpoint_cb=save_cb,
                                    checkpoint_every_s=every_s)

            res, snap = self._mine_checked_out(entry, runner)
            if res is not None:  # a cap overflow on re-mine: fall through
                if stats_out is not None:
                    stats_out.update(snap)
                    # classic engines carry no 'fused' key in their own
                    # stats; artifact consumers key the route on it
                    stats_out.setdefault("fused", False)
                    stats_out["store_cache_hit"] = True
                return res
            with self._lock:
                self._entries.pop(key, None)
            # a cached queue engine that overflowed would overflow again
            # deterministically on identical inputs — tell the rebuild to
            # skip the queue attempt instead of doubling the device work.
            # A checkpointed overflow resumes in the rebuilt classic
            # engine from the queue segments' last snapshot (shared
            # frontier format, same fingerprint).
            if stats_out is not None:
                stats_out["fused_overflow"] = True
            res, engine = self._build_and_mine(
                db, minsup_abs, mesh=mesh, stats_out=stats_out,
                max_pattern_itemsets=max_pattern_itemsets,
                shape_buckets=shape_buckets, fused=fused,
                checkpoint=checkpoint, skip_queue=True)
            if stats_out is not None:
                stats_out["store_cache_hit"] = False
            if engine is not None:
                self._insert_engine(key, engine)
            return res

        res, engine = self._build_and_mine(
            db, minsup_abs, mesh=mesh, stats_out=stats_out,
            max_pattern_itemsets=max_pattern_itemsets,
            shape_buckets=shape_buckets, fused=fused, checkpoint=checkpoint)
        if stats_out is not None:
            stats_out["store_cache_hit"] = False
        if engine is not None:
            self._insert_engine(key, engine)
        return res

    def _build_and_mine(self, db, minsup_abs, *, mesh, stats_out,
                        max_pattern_itemsets, shape_buckets, fused,
                        checkpoint=None, skip_queue=False):
        """mine_spade_tpu's routing, but keeping the engine object.

        ``skip_queue``: the caller already observed this exact workload
        overflow the queue engine's caps (a cached engine's re-mine) —
        don't pay for a second deterministic overflow.
        """
        from spark_fsm_tpu.data.vertical import build_vertical
        from spark_fsm_tpu.models._common import load_checkpoint
        from spark_fsm_tpu.models.spade_queue import (
            QueueSpadeTPU, queue_eligible)
        from spark_fsm_tpu.models.spade_tpu import SpadeTPU

        vdb = build_vertical(db, min_item_support=minsup_abs)
        if vdb.n_items == 0:
            return [], None
        ekw = dict(mesh=mesh, max_pattern_itemsets=max_pattern_itemsets,
                   shape_buckets=shape_buckets)
        if not skip_queue and fused in ("auto", "queue") and (
                fused == "queue"
                or queue_eligible(vdb, mesh=mesh,
                                  shape_buckets=shape_buckets)):
            qeng = QueueSpadeTPU(vdb, minsup_abs, **ekw)
            q_resume, q_save, q_every = load_checkpoint(
                checkpoint, qeng.frontier_fingerprint())
            res = qeng.mine(resume=q_resume, checkpoint_cb=q_save,
                            checkpoint_every_s=q_every)
            if res is not None:
                if stats_out is not None:
                    stats_out.update(qeng.stats)
                return res, qeng
            if stats_out is not None:
                stats_out["fused_overflow"] = True
        if fused == "auto" and checkpoint is None:
            # mirror mine_spade_tpu: the dense engine is "auto"'s second
            # try — queue-ineligible, queue-overflowed (this mine or a
            # cached one, per skip_queue), it must still WIN the route
            # where eligible.  It rebuilds its store per mine(), so it is
            # not worth caching — degrading it to the classic DFS would
            # re-add one readback per wave on tunneled TPUs.
            from spark_fsm_tpu.models.spade_fused import (
                FusedSpadeTPU, fused_eligible)
            if fused_eligible(vdb, mesh=mesh, shape_buckets=shape_buckets):
                feng = FusedSpadeTPU(vdb, minsup_abs, **ekw)
                res = feng.mine()
                if res is not None:
                    if stats_out is not None:
                        stats_out.update(feng.stats)
                    return res, None
                if stats_out is not None:
                    stats_out["fused_overflow"] = True
        elif fused == "auto" and stats_out is not None:
            # the dense engine alone has no resumable frontier; a
            # checkpointed job that would have routed to it degrades to
            # the classic engine — flagged, not fatal (mine_spade_tpu's
            # checkpoint-unsupported convention)
            from spark_fsm_tpu.models.spade_fused import fused_eligible
            if fused_eligible(vdb, mesh=mesh, shape_buckets=shape_buckets):
                stats_out["fused_skipped"] = "checkpoint"
        eng = SpadeTPU(vdb, minsup_abs, **ekw)
        resume, save_cb, every_s = load_checkpoint(
            checkpoint, eng.frontier_fingerprint())
        res = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
        if stats_out is not None:
            stats_out.update(eng.stats)
            stats_out.setdefault("fused", False)
        return res, eng

class CSpadeEngineCache(_HbmBudgetCache):
    """The cSPADE half of the repeat-``/train`` story (SpadeEngineCache
    covers plain SPADE, TsrEngineCache covers rules).

    A :class:`~spark_fsm_tpu.models.spade_constrained.ConstrainedSpadeTPU`
    keeps its item store and max-start state pool in HBM across
    ``mine()`` calls exactly like the classic engine, so a repeat
    constrained mine was re-paying the token upload + scatter-build +
    engine construction (~2 s of full-Gazelle prep per ``/train``,
    BENCH_SCALE config 4 cold-vs-warm) for nothing.  The fingerprint
    folds in maxgap/maxwindow: the constraint pair selects a DIFFERENT
    compiled kernel set (``_cspade_fns``) and a different enumeration,
    so two mines differing only in constraints must never share an
    entry.  Checkpointed constrained jobs fall through uncached (the
    per-request resume plumbing stays on the wrapper path).

    Budget: half the plain cache's fraction — constrained engines are
    positions-wide (int8/16 pools), and the TWO module-level caches'
    pinned bytes must jointly leave room for a live queue working set
    (see _HbmBudgetCache)."""

    _BUDGET_FRACTION = 0.125

    def mine(self, db: SequenceDB, minsup_abs: int, *,
             maxgap: Optional[int] = None,
             maxwindow: Optional[int] = None,
             mesh=None, stats_out: Optional[dict] = None,
             max_pattern_itemsets: Optional[int] = None,
             shape_buckets: bool = False,
             checkpoint=None,
             **kwargs) -> List[PatternResult]:
        from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu

        def fallback():
            return mine_cspade_tpu(
                db, minsup_abs, maxgap=maxgap, maxwindow=maxwindow,
                mesh=mesh, stats_out=stats_out,
                max_pattern_itemsets=max_pattern_itemsets,
                shape_buckets=shape_buckets, checkpoint=checkpoint,
                **kwargs)

        if kwargs or checkpoint is not None:
            # explicit engine knobs the cache does not key, or a
            # checkpointed job: uncached wrapper
            return fallback()
        return self._mine_guarded(
            lambda: self._mine_cached(
                db, minsup_abs, maxgap=maxgap, maxwindow=maxwindow,
                mesh=mesh, stats_out=stats_out,
                max_pattern_itemsets=max_pattern_itemsets,
                shape_buckets=shape_buckets),
            fallback)

    def _mine_cached(self, db, minsup_abs, *, maxgap, maxwindow, mesh,
                     stats_out, max_pattern_itemsets, shape_buckets):
        key = (db_fingerprint(db), int(minsup_abs), maxgap, maxwindow,
               mesh, max_pattern_itemsets, bool(shape_buckets))
        entry = self._checkout(key)
        if entry is not None:
            res, snap = self._mine_checked_out(entry)
            if stats_out is not None:
                stats_out.update(snap)
                stats_out["store_cache_hit"] = True
            return res

        from spark_fsm_tpu.data.vertical import build_vertical
        from spark_fsm_tpu.models.spade_constrained import (
            ConstrainedSpadeTPU)

        vdb = build_vertical(db, min_item_support=minsup_abs)
        if vdb.n_items == 0:
            if stats_out is not None:
                stats_out["store_cache_hit"] = False
            return []
        eng = ConstrainedSpadeTPU(
            vdb, minsup_abs, maxgap=maxgap, maxwindow=maxwindow, mesh=mesh,
            max_pattern_itemsets=max_pattern_itemsets,
            shape_buckets=shape_buckets)
        res = eng.mine()
        if stats_out is not None:
            stats_out.update(eng.stats)
            stats_out["store_cache_hit"] = False
        self._insert_engine(key, eng)
        return res


class TsrEngineCache(_EngineCacheBase):
    """LRU TSR-engine cache with exclusive checkout (the TSR half of the
    repeat-``/train`` story; SpadeEngineCache covers plain SPADE).

    A TSR engine holds NO persistent HBM between mines — each deepening
    round's prefix/suffix prep stores are transients — so what a hit
    skips is the full vertical build + token indexing (~7.4 s of host
    work at Kosarak scale, BENCH_SCALE config 3 ``vertical_build_s``)
    plus engine construction, paid today on EVERY repeat ``/train`` of
    the framework's longest jobs.  Entries are therefore capped by
    COUNT (each holds ~100 MB of host token arrays at Kosarak scale),
    not by the HBM budget; the same content-fingerprint key discipline
    as SpadeEngineCache makes staleness impossible by construction."""

    def __init__(self, max_entries: int = 2):
        super().__init__()
        self._max = int(max_entries)

    def mine(self, db: SequenceDB, k: int, minconf: float, *,
             max_side=None, mesh=None, stats_out: Optional[dict] = None,
             **kwargs) -> List:
        from spark_fsm_tpu.models.tsr import mine_tsr_tpu

        return self._mine_guarded(
            lambda: self._mine_cached(db, k, minconf, max_side=max_side,
                                      mesh=mesh, stats_out=stats_out,
                                      **kwargs),
            lambda: mine_tsr_tpu(db, k, minconf, max_side=max_side,
                                 mesh=mesh, stats_out=stats_out, **kwargs))

    def _mine_cached(self, db: SequenceDB, k: int, minconf: float, *,
                     max_side=None, mesh=None,
                     stats_out: Optional[dict] = None, **kwargs) -> List:
        from spark_fsm_tpu.data.vertical import build_vertical
        from spark_fsm_tpu.models.tsr import TsrTPU

        key = (db_fingerprint(db), int(k), float(minconf), max_side, mesh,
               tuple(sorted(kwargs.items())))
        entry = self._checkout(key)
        if entry is not None:
            res, snap = self._mine_checked_out(entry)
            if stats_out is not None:
                stats_out.update(snap)
                stats_out["store_cache_hit"] = True
            return res

        vdb = build_vertical(db, min_item_support=1)
        if vdb.n_items == 0:
            if stats_out is not None:
                stats_out["store_cache_hit"] = False
            return []
        eng = TsrTPU(vdb, k, minconf, max_side=max_side, mesh=mesh,
                     **kwargs)
        res = eng.mine()
        if stats_out is not None:
            stats_out.update(eng.stats)
            stats_out["store_cache_hit"] = False
        self._scrub(eng)
        self._insert(key, eng, 0)
        return res

    def _scrub(self, engine) -> None:
        # a per-bucket kernel downgrade in the mine's FINAL round leaves
        # the engine-layout prep pair on device (_jnp_prep is cleared at
        # ROUND start, tsr._mine_restricted) — dropping it here keeps
        # the "cached TSR engines hold no persistent HBM" contract the
        # count-based (not byte-based) eviction relies on
        engine._jnp_prep = None
        engine._jnp_chunk = None

    def _evict_locked(self, new_key) -> None:
        for ek in list(self._entries):
            if len(self._entries) <= self._max:
                break
            e = self._entries[ek]
            if e.busy or ek == new_key:
                continue
            del self._entries[ek]
            self.stats["evictions"] += 1


# process-wide caches the service plugin layer uses
spade_engine_cache = SpadeEngineCache()
cspade_engine_cache = CSpadeEngineCache()
tsr_engine_cache = TsrEngineCache()

_BREAKER_STATE_CODE = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                       CircuitBreaker.OPEN: 2}


def _collect_metrics():
    """fsm_devcache_* / fsm_breaker_* families for the unified registry
    — the /admin/stats per-cache blocks and /admin/health ``breakers``
    block are aliases of these (cache labels reuse their JSON key
    names: store_cache / cspade_cache / tsr_cache)."""
    caches = (("store_cache", spade_engine_cache),
              ("cspade_cache", cspade_engine_cache),
              ("tsr_cache", tsr_engine_cache))
    fams = []
    for key in ("hits", "misses", "busy_misses", "evictions",
                "breaker_fallbacks"):
        fams.append((f"fsm_devcache_{key}_total", "counter", "",
                     [({"cache": name}, c.stats.get(key, 0))
                      for name, c in caches]))
    snaps = [(name, c.breaker.snapshot()) for name, c in caches]
    fams.append(("fsm_breaker_state", "gauge",
                 "0=closed 1=half-open 2=open",
                 [({"cache": name}, _BREAKER_STATE_CODE[s["state"]])
                  for name, s in snaps]))
    fams.append(("fsm_breaker_opens_total", "counter", "",
                 [({"cache": name}, s["opens"]) for name, s in snaps]))
    return fams


obs.REGISTRY.register_collector("devcache", _collect_metrics)
