"""NumPy reference implementations of the SPADE bitmap primitives.

These define the exact semantics the TPU kernels (ops/bitops_jax.py,
ops/pallas_kernels.py) must reproduce bit-for-bit; the oracle miner
(models/oracle.py) is built on them.  SURVEY.md sec 2.3 step 4:

- i-extension: bitmap AND at identical positions;
- s-extension: transform the prefix bitmap so that, per sequence, all bits
  strictly after the FIRST set bit are set ("first-occurrence postfix
  mask"), then AND with the item bitmap;
- support: number of sequences whose slice of the result is nonzero.

Bit order: position p lives in word p // 32, bit p % 32, LSB-first, so
"later position" = "more significant bit" and the postfix mask is a carry
chain toward higher words.
"""

from __future__ import annotations

import numpy as np

U32 = np.uint32
FULL = np.uint32(0xFFFFFFFF)


def prefix_or_word(w: np.ndarray) -> np.ndarray:
    """Within-word inclusive prefix OR: out bit p = OR of bits 0..p of w."""
    w = w.astype(U32, copy=True)
    for shift in (1, 2, 4, 8, 16):
        w |= w << U32(shift)
    return w


def sext_transform(b: np.ndarray) -> np.ndarray:
    """First-occurrence postfix mask over the last (word) axis.

    out bit p = 1 iff some bit q < p of the same sequence is set in ``b``
    (equivalently: p is strictly after the first set bit).
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = (prefix_or_word(w) << U32(1)) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def i_extend(prefix_bitmap: np.ndarray, item_bitmap: np.ndarray) -> np.ndarray:
    """Itemset extension: both end at the same position."""
    return prefix_bitmap & item_bitmap


def s_extend(prefix_bitmap: np.ndarray, item_bitmap: np.ndarray) -> np.ndarray:
    """Sequence extension: item strictly after the prefix's first end."""
    return sext_transform(prefix_bitmap) & item_bitmap


def support(bitmap: np.ndarray) -> np.ndarray:
    """Sequence-count support: #sequences with any set bit.

    bitmap: [..., n_seq, n_words] -> [...] int64.
    """
    return np.count_nonzero((np.asarray(bitmap) != 0).any(axis=-1), axis=-1)


def prefix_or_incl(b: np.ndarray) -> np.ndarray:
    """Inclusive prefix OR: out bit p = 1 iff some bit q <= p set.

    TSR building block: prefix_or_incl(id-list(x)) bit p says "x has
    occurred by position p"; AND over x in X gives "all of X occurred by p"
    (SURVEY.md sec 2.4 occurrence maps, bitmap formulation).
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = prefix_or_word(w) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def suffix_or_word(w: np.ndarray) -> np.ndarray:
    """Within-word inclusive suffix OR: out bit p = OR of bits p..31 of w."""
    w = w.astype(U32, copy=True)
    for shift in (1, 2, 4, 8, 16):
        w |= w >> U32(shift)
    return w


def suffix_or_incl(b: np.ndarray) -> np.ndarray:
    """Inclusive suffix OR: out bit p = 1 iff some bit q >= p set.

    suffix_or_incl(id-list(y)) bit p says "y occurs at or after p"; AND over
    y in Y gives "all of Y occur at or after p".
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1] - 1, -1, -1):
        w = b[..., j]
        out[..., j] = suffix_or_word(w) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def shift_up_one(b: np.ndarray) -> np.ndarray:
    """Shift the whole per-sequence bitvector one position higher (bit p ->
    bit p+1), with carries across words.  (A << 1) & C != 0 is the TSR rule
    containment test: exists p with all-X-by-(p-1) and all-Y-at->=p."""
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=U32)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = ((w << U32(1)) & FULL) | carry
        carry = w >> U32(31)
    return out


def popcount(w: np.ndarray) -> np.ndarray:
    """Per-word population count (SWAR), uint32 -> int32 same shape."""
    w = np.asarray(w, dtype=U32).copy()
    w -= (w >> U32(1)) & U32(0x55555555)
    w = (w & U32(0x33333333)) + ((w >> U32(2)) & U32(0x33333333))
    w = (w + (w >> U32(4))) & U32(0x0F0F0F0F)
    return ((w * U32(0x01010101)) >> U32(24)).astype(np.int32)


def tail_mask(n_valid: int, n_words: int) -> np.ndarray:
    """[n_words] uint32 mask keeping only bits 0..n_valid-1 of the
    flattened bit axis (bit ``p`` lives in word ``p // 32``).  A bitmap
    axis padded up to a word multiple carries ``n_words*32 - n_valid``
    padding bits in its tail word; any POPCOUNT-style reduction must
    AND this mask in first — supports (any-bit tests) survive padding,
    counts do not."""
    out = np.zeros(n_words, dtype=U32)
    full = min(n_valid // 32, n_words)
    out[:full] = FULL
    rem = n_valid - full * 32
    if 0 < rem and full < n_words:
        out[full] = (U32(1) << U32(rem)) - U32(1)
    return out


def masked_popcount(b: np.ndarray, n_valid: int) -> np.ndarray:
    """[..., n_words] -> [...] int64: total set bits at VALID positions.

    The tail-word mask is load-bearing, not defensive: SPAM's
    s-extension shift (``sext_transform``) deliberately saturates every
    bit above the first occurrence — including the padding bits beyond
    the true position capacity in the tail word — so a naive popcount
    over a transformed bitmap overcounts by up to 31 per sequence
    whenever the position axis is not a multiple of the word width
    (the bug this helper fixes; pinned in tests/test_bitops_np.py)."""
    b = np.asarray(b, dtype=U32)
    return popcount(b & tail_mask(n_valid, b.shape[-1])).sum(
        axis=-1, dtype=np.int64)


def pack_seq_bits(active: np.ndarray) -> np.ndarray:
    """Pack a boolean per-sequence indicator [..., n_seq] into LSB-first
    uint32 words [..., ceil(n_seq/32)], zero-padding the tail word —
    the SPAM support formulation: support = popcount(packed words).
    The explicit zero pad is the correct tail handling when the
    SEQUENCE count is not a multiple of the word width (garbage padding
    lanes would be counted as support)."""
    active = np.asarray(active, dtype=bool)
    n_seq = active.shape[-1]
    n_w = max(1, -(-n_seq // 32))
    pad = n_w * 32 - n_seq
    if pad:
        active = np.concatenate(
            [active, np.zeros(active.shape[:-1] + (pad,), bool)], axis=-1)
    bits = active.reshape(active.shape[:-1] + (n_w, 32)).astype(U32)
    weights = (U32(1) << np.arange(32, dtype=U32))
    return (bits * weights).sum(axis=-1).astype(U32)


def support_popcount(bitmap: np.ndarray) -> np.ndarray:
    """Sequence-count support via the SPAM popcount formulation:
    collapse words -> per-sequence alive bit -> pack over the sequence
    axis -> popcount.  Bit-identical to :func:`support` (the any/count
    spelling); exists so the vectorized popcount path has a numpy
    reference the device engine is pinned against."""
    alive = (np.asarray(bitmap) != 0).any(axis=-1)
    packed = pack_seq_bits(alive)
    return popcount(packed).sum(axis=-1).astype(np.int64)


def diffset_count(parent_bitmap: np.ndarray,
                  child_bitmap: np.ndarray) -> np.ndarray:
    """dEclat diffset size: #sequences alive in the parent but dead in
    the child, [..., n_seq, n_words] -> [...] int64.

    Every temporal join ANDs the (possibly transformed) parent row, so
    the child's alive-set is a SUBSET of the parent row's alive-set and
    ``support(child) == support(parent_row) - diffset_count`` holds
    EXACTLY (integer identity, no approximation) — the deep-extension
    support formulation of :func:`support_from_diffset`.  The parent
    here is the JOINED-AGAINST row: the plain prefix bitmap for an
    i-extension, the ``sext_transform``-ed one for an s-extension."""
    pa = (np.asarray(parent_bitmap) != 0).any(axis=-1)
    ca = (np.asarray(child_bitmap) != 0).any(axis=-1)
    return popcount(pack_seq_bits(pa & ~ca)).sum(axis=-1).astype(np.int64)


def support_from_diffset(parent_support, diffset_size):
    """dEclat support: ``support(parent_row) - |diffset|``.  Exact
    whenever the child's alive-set is a subset of the parent's — true
    by construction for every s/i-extension (see diffset_count)."""
    return parent_support - diffset_size


def first_set_positions(b: np.ndarray) -> np.ndarray:
    """Per-sequence index of the first set bit, or n_words*32 if none.

    b: [..., n_words] -> [...] int32.  Used by TSR occurrence logic.
    """
    b = np.asarray(b, dtype=U32)
    n_words = b.shape[-1]
    pos = np.full(b.shape[:-1], n_words * 32, dtype=np.int32)
    for j in range(n_words - 1, -1, -1):
        w = b[..., j]
        nz = w != 0
        # int64 to avoid uint32->float pitfalls in log2-style tricks
        ww = w.astype(np.int64)
        lsb = (ww & -ww).astype(np.uint64)
        low = np.where(nz, (np.log2(np.maximum(lsb, 1).astype(np.float64))).astype(np.int32), 0)
        pos = np.where(nz, j * 32 + low, pos)
    return pos
