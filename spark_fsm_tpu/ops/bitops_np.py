"""NumPy reference implementations of the SPADE bitmap primitives.

These define the exact semantics the TPU kernels (ops/bitops_jax.py,
ops/pallas_kernels.py) must reproduce bit-for-bit; the oracle miner
(models/oracle.py) is built on them.  SURVEY.md sec 2.3 step 4:

- i-extension: bitmap AND at identical positions;
- s-extension: transform the prefix bitmap so that, per sequence, all bits
  strictly after the FIRST set bit are set ("first-occurrence postfix
  mask"), then AND with the item bitmap;
- support: number of sequences whose slice of the result is nonzero.

Bit order: position p lives in word p // 32, bit p % 32, LSB-first, so
"later position" = "more significant bit" and the postfix mask is a carry
chain toward higher words.
"""

from __future__ import annotations

import numpy as np

U32 = np.uint32
FULL = np.uint32(0xFFFFFFFF)


def prefix_or_word(w: np.ndarray) -> np.ndarray:
    """Within-word inclusive prefix OR: out bit p = OR of bits 0..p of w."""
    w = w.astype(U32, copy=True)
    for shift in (1, 2, 4, 8, 16):
        w |= w << U32(shift)
    return w


def sext_transform(b: np.ndarray) -> np.ndarray:
    """First-occurrence postfix mask over the last (word) axis.

    out bit p = 1 iff some bit q < p of the same sequence is set in ``b``
    (equivalently: p is strictly after the first set bit).
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = (prefix_or_word(w) << U32(1)) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def i_extend(prefix_bitmap: np.ndarray, item_bitmap: np.ndarray) -> np.ndarray:
    """Itemset extension: both end at the same position."""
    return prefix_bitmap & item_bitmap


def s_extend(prefix_bitmap: np.ndarray, item_bitmap: np.ndarray) -> np.ndarray:
    """Sequence extension: item strictly after the prefix's first end."""
    return sext_transform(prefix_bitmap) & item_bitmap


def support(bitmap: np.ndarray) -> np.ndarray:
    """Sequence-count support: #sequences with any set bit.

    bitmap: [..., n_seq, n_words] -> [...] int64.
    """
    return np.count_nonzero((np.asarray(bitmap) != 0).any(axis=-1), axis=-1)


def prefix_or_incl(b: np.ndarray) -> np.ndarray:
    """Inclusive prefix OR: out bit p = 1 iff some bit q <= p set.

    TSR building block: prefix_or_incl(id-list(x)) bit p says "x has
    occurred by position p"; AND over x in X gives "all of X occurred by p"
    (SURVEY.md sec 2.4 occurrence maps, bitmap formulation).
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = prefix_or_word(w) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def suffix_or_word(w: np.ndarray) -> np.ndarray:
    """Within-word inclusive suffix OR: out bit p = OR of bits p..31 of w."""
    w = w.astype(U32, copy=True)
    for shift in (1, 2, 4, 8, 16):
        w |= w >> U32(shift)
    return w


def suffix_or_incl(b: np.ndarray) -> np.ndarray:
    """Inclusive suffix OR: out bit p = 1 iff some bit q >= p set.

    suffix_or_incl(id-list(y)) bit p says "y occurs at or after p"; AND over
    y in Y gives "all of Y occur at or after p".
    """
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=bool)
    for j in range(b.shape[-1] - 1, -1, -1):
        w = b[..., j]
        out[..., j] = suffix_or_word(w) | np.where(carry, FULL, U32(0))
        carry |= w != 0
    return out


def shift_up_one(b: np.ndarray) -> np.ndarray:
    """Shift the whole per-sequence bitvector one position higher (bit p ->
    bit p+1), with carries across words.  (A << 1) & C != 0 is the TSR rule
    containment test: exists p with all-X-by-(p-1) and all-Y-at->=p."""
    b = np.asarray(b, dtype=U32)
    out = np.empty_like(b)
    carry = np.zeros(b.shape[:-1], dtype=U32)
    for j in range(b.shape[-1]):
        w = b[..., j]
        out[..., j] = ((w << U32(1)) & FULL) | carry
        carry = w >> U32(31)
    return out


def first_set_positions(b: np.ndarray) -> np.ndarray:
    """Per-sequence index of the first set bit, or n_words*32 if none.

    b: [..., n_words] -> [...] int32.  Used by TSR occurrence logic.
    """
    b = np.asarray(b, dtype=U32)
    n_words = b.shape[-1]
    pos = np.full(b.shape[:-1], n_words * 32, dtype=np.int32)
    for j in range(n_words - 1, -1, -1):
        w = b[..., j]
        nz = w != 0
        # int64 to avoid uint32->float pitfalls in log2-style tricks
        ww = w.astype(np.int64)
        lsb = (ww & -ww).astype(np.uint64)
        low = np.where(nz, (np.log2(np.maximum(lsb, 1).astype(np.float64))).astype(np.int32), 0)
        pos = np.where(nz, j * 32 + low, pos)
    return pos
