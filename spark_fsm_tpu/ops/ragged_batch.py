"""Ragged candidate super-batching: pow2 launch geometries, shared compiles.

The deep TSR path and the late phase of queue mines both produce RAGGED
work: candidate sets whose per-item width (the km side-size bucket, or
the live queue frontier) varies freely while every compiled program has
a static shape.  Before this layer each ragged set dispatched one launch
per (km bucket x dispatch), so the service-default unlimited-side TSR
mine paid 371 kernel launches where the max_side=2 mine paid 41
(BENCH_SCALE 3 vs 3d) — per-launch dispatch latency and per-launch
underfill, not kernel throughput, were the bill.

This module is the ONE packing policy for that work:

- **pow2 super-batch geometries**: every launch runs at a (km, width)
  drawn from a finite pow2 ladder (:func:`superbatch_geometries`), so
  the compiled-program set stays log-sized and enumerable — the prewarm
  driver (service/prewarm.py) walks the same ladder, which is how the
  PR-1 zero-fresh-compile guarantee survives super-batching.
- **mixed-km packing with per-lane km tags** (:func:`plan_launches`):
  per-km pools first split greedily into FULL pow2 launches at their own
  km (100% fill, the measured-best policy), then the per-km TAILS merge
  into shared super-batches at the largest participating km.  A lane of
  side <= skm < km fits the km-wide xy layout trivially (unused slots
  are -1 -> the all-ones pad row), so merging is always CORRECT; the
  cost model below decides when it is also CHEAPER.
- **a cost model, not a heuristic flag**: kernel wall is ~linear in
  width x km (every padded lane streams its km prefix+suffix blocks),
  and every launch pays a fixed dispatch cost.  A merge is taken iff
  ``merged_width x km_geom <= separate_widths x kms + overhead`` with
  the dispatch overhead expressed in the same traffic units
  (:data:`LAUNCH_OVERHEAD_UNITS`) — so a 900-candidate km1 tail is
  NEVER dragged into a km8 geometry (8x its traffic), while four
  64-candidate tails collapse into one launch (4 dispatches -> 1).
- **double-buffered host staging** (:class:`XYStager`): per-geometry
  reusable xy buffers, ping-ponged so the previous launch's possibly
  in-flight host->device copy is never overwritten while the next
  launch packs — candidate build overlaps device eval instead of
  serializing in front of it.
- **late-wave geometry for the queue engine** (:func:`late_wave_nb`):
  the same pow2-ladder idea applied to wave width — when the live
  frontier drops far below ``nb``, the queue program switches to a
  narrow wave geometry, merging what would be many underfilled
  full-width waves into well-filled narrow ones.

The planner is pure host arithmetic (no jax import): models/tsr.py
drives it for both the Pallas kernel path and the jnp fallback (their
width caps differ — the jnp evaluator's live-temp footprint narrows
1/km), and models/spade_queue.py shares :func:`late_wave_nb`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_fsm_tpu.utils import obs  # host-only, keeps the no-jax contract

_PLAN_LAUNCHES = obs.REGISTRY.counter(
    "fsm_planner_launches_total", "launches emitted by the ragged packer")
_PLAN_SUPERBATCHES = obs.REGISTRY.counter(
    "fsm_planner_superbatches_total",
    "mixed-km launches emitted by the ragged packer")

# Fixed per-launch dispatch cost in TRAFFIC UNITS (one unit = one lane
# streaming one km's prefix+suffix blocks over the sequence axis).  At
# the headline Kosarak geometry a km1 lane costs ~10.5 us of kernel wall
# (85.8 ms / 8192 lanes over 990k seqs, KERNELS.json) and a dispatch
# costs ~5 ms locally (tens of ms tunneled), so ~512 units is the
# conservative local figure at FULL scale; merges/pads cheaper than this
# always win, costlier ones never taken.  :func:`overhead_units` scales
# the figure to the actual sequence-axis size — at dryrun scale a lane
# costs nanoseconds, so the same 5 ms dispatch is worth ~10^5 lanes of
# pad and the planner correctly collapses everything it can.
LAUNCH_OVERHEAD_UNITS = 512

# Measured per-(seq word x lane x km) kernel cost anchoring the unit:
# 85.8e-3 s / 8192 lanes / 990_000 seqs (KERNELS.json rule_supports).
LANE_SEC_PER_SEQWORD = 85.8e-3 / 8192 / 990_000

# Per-kernel lane-rate anchors (KERNELS.json): each kernel family's
# measured wall divided by its lane x seq-word work.  The pair/extend
# "lane" is one (parent, item) output cell — 43.35 ms over 2048x384
# cells streaming 77824 seq-words (pair_supports headline row).  The
# fused extend_prune kernel re-uses the pair anchor STRUCTURALLY: its
# extra epilogue is ~6 VPU ops per output cell ONCE vs 4 ops per
# seq-word accumulated over every sequence block — a 1.5/(S*W) relative
# add (~2e-5 at the headline S), below measurement noise, so the
# committed pair wall is the honest anchor until a TPU session
# re-measures it (bench_kernels.py writes the entry; the 2026-08-03
# structural-note precedent).  Like DISPATCH_SEC, these are COMMITTED
# constants: the live fsm_costmodel_drift_ratio EWMA (PR 6) is what
# absorbs machine-to-machine drift at plan time — drift_factor()
# multiplies the overhead regardless of which anchor row priced the
# lane, so a stale anchor inflates overhead_units uniformly instead of
# skewing one kernel family against another.
KERNEL_LANE_SEC = {
    "rule_supports": LANE_SEC_PER_SEQWORD,
    "pair_supports": 43.35e-3 / (2048 * 384) / 77_824,
    "extend_prune": 43.35e-3 / (2048 * 384) / 77_824,
}


def lane_sec_per_seqword(kernel: str = "rule_supports") -> float:
    """The committed lane-rate anchor for one kernel family (falls back
    to the rule_supports unit for unknown names — the conservative,
    largest per-lane figure)."""
    return KERNEL_LANE_SEC.get(kernel, LANE_SEC_PER_SEQWORD)


# Conservative per-dispatch fixed cost (local PCIe; a tunneled backend
# runs ~10x this, which only makes merging MORE right).
DISPATCH_SEC = 0.005


# --- live overhead recalibration -------------------------------------------
# The KERNELS.json-anchored DISPATCH_SEC above is a COMMITTED constant:
# correct on the machine that measured it, stale anywhere else (a
# tunneled TPU runs ~10x, a CPU CI box further still).  The flight
# recorder already measures the truth — fsm_costmodel_drift_ratio is
# the EWMA of measured/predicted dispatch wall — so plan-time overhead
# scales DISPATCH_SEC by that live ratio instead of trusting the
# constant.  The factor is quantized to pow2 steps and clamped [1, 16]:
# quantization keeps launch plans stable against run-to-run timing
# noise (an un-quantized factor would make every pinned launch-budget
# counter nondeterministic), scaling only UP keeps a drifting gauge
# from ever shrinking the overhead below its measured-anchor floor.
# ``set_overhead_calibration(False)`` pins the raw constant — the
# launch-budget tests and bench_smoke pin it so their committed
# dispatch-shape counters stay exact.

_CALIBRATE = True
_DRIFT_FACTOR_CAP = 16


def set_overhead_calibration(enabled: bool) -> None:
    global _CALIBRATE
    _CALIBRATE = bool(enabled)


def drift_factor() -> int:
    """Quantized (pow2) clamp of the live cost-model drift EWMA — the
    multiplier applied to DISPATCH_SEC at plan time.  1 until the first
    calibration sample lands (or when calibration is pinned off)."""
    if not _CALIBRATE:
        return 1
    drift = obs.costmodel_drift()
    if drift is None or drift <= 1.0:
        return 1
    return min(_DRIFT_FACTOR_CAP, floor_pow2(int(drift)))


def calibrated_dispatch_s() -> float:
    """DISPATCH_SEC scaled by the live drift EWMA (see above)."""
    return DISPATCH_SEC * drift_factor()


def overhead_units(n_seq: int, n_words: int,
                   dispatch_s: Optional[float] = None,
                   kernel: str = "rule_supports") -> int:
    """Per-launch overhead in traffic units for a given sequence-axis
    size: how many padded lanes one saved dispatch is worth.  Clamped so
    degenerate geometries cannot zero out either term of the planner's
    cost model.  ``dispatch_s=None`` (the engines' plan-time default)
    resolves to :func:`calibrated_dispatch_s` — the committed constant
    recalibrated by the live ``fsm_costmodel_drift_ratio`` EWMA.
    ``kernel`` selects the lane-rate anchor (KERNEL_LANE_SEC): the same
    saved dispatch is worth more pad lanes of a cheaper-per-lane
    kernel."""
    if dispatch_s is None:
        dispatch_s = calibrated_dispatch_s()
    lane_s = max(1e-12,
                 n_seq * max(1, n_words) * lane_sec_per_seqword(kernel))
    return max(64, min(1 << 20, int(dispatch_s / lane_s)))


# The dispatch quantum the 8192-lane default width encodes: the
# measured wall of a full-width km1 launch at the full Kosarak sequence
# axis (KERNELS.json rule_supports).  A launch should cost ~this much
# device time regardless of S — the lane count that buys it scales
# inversely with the sequence axis.
QUANTUM_SEC = 85.8e-3


def dispatch_quantum_lanes(n_seq: int, n_words: int,
                           quantum_s: float = QUANTUM_SEC,
                           lo: int = 8192, hi: int = 16384) -> int:
    """Dispatch-efficiency width ceiling in lanes for a given
    sequence-axis size: the pow2 lane count whose launch costs about
    ``quantum_s`` of device time.  Equals the measured-best 8192 at the
    full Kosarak axis (the anchor) and grows as the axis shrinks — a
    dryrun-scale mine packs the same device time per dispatch instead
    of paying full-scale dispatch granularity for microseconds of
    work.  ``hi`` bounds the best-first STALENESS cost: candidates pop
    with the minsup of dispatch time, so the speculation window (width
    x pipeline depth) must stay a small multiple of the full-scale
    window — an unbounded quantum measured 1.9x the evaluations at
    dryrun scale.  Memory caps (the engine's budget arithmetic) still
    apply on top; this is only the efficiency term."""
    lane_s = max(1e-12, n_seq * max(1, n_words) * LANE_SEC_PER_SEQWORD)
    return max(lo, min(hi, floor_pow2(int(quantum_s / lane_s) + 1)))

def estimate_seconds(traffic_units: int, n_launches: int, n_seq: int,
                     n_words: int, dispatch_s: float = DISPATCH_SEC) -> float:
    """Predicted device wall for a dispatch of ``n_launches`` launches
    streaming ``traffic_units`` lane-km units — the same KERNELS.json-
    anchored terms the packer's cost model trades off, exposed so the
    dispatch watchdog (utils/watchdog.py) can derive a deadline from
    the planner's OWN arithmetic instead of a guessed constant."""
    lane_s = n_seq * max(1, n_words) * LANE_SEC_PER_SEQWORD
    return max(0, traffic_units) * lane_s + max(1, n_launches) * dispatch_s


# The km side-size ladder enumerated for prewarm.  Rule sides wider than
# 8 items are possible in principle (unlimited max_side over a rich
# alphabet) but unobserved in every eval config; a km16 launch would
# compile live and surface in /admin/shapes drift — a signal, not a bug.
KM_LADDER = (1, 2, 4, 8)


def next_pow2(n: int) -> int:
    k = 1
    while k < n:
        k *= 2
    return k


def floor_pow2(n: int) -> int:
    return 1 << (int(n).bit_length() - 1) if n >= 1 else 1


@dataclasses.dataclass
class Launch:
    """One planned super-batch launch.

    ``km``: the launch GEOMETRY (compiled xy minor width) — the max of
    its lanes' own km buckets.  ``width``: padded pow2 lane count (the
    compiled candidate axis).  ``rows``: candidate indices, in lane
    order.  ``kms``: each lane's OWN km bucket (the per-lane km tag —
    lanes with ``kms[j] < km`` are borrowed/merged lanes riding a wider
    geometry).  ``jobs``: each lane's OWN job tag (parallel to ``rows``;
    None for single-job plans) — the cross-job fusion broker
    (service/fusion.py) plans launches over candidates pooled from
    SEVERAL concurrent mines, and the per-lane job tag is what lets its
    readback demux each lane's (sup, supx) back to the job that owns
    it.  ``part``: the equivalence-class partition the launch belongs
    to (parallel/partition.py; None outside partitioned mines) — a
    partitioned mine's launches are planned per partition under that
    partition's own caps, and the tag keys the per-partition dispatch
    accounting the scaling bench exports.
    """

    km: int
    width: int
    rows: List[int]
    kms: List[int]
    jobs: Optional[List[int]] = None
    part: Optional[int] = None

    @property
    def traffic_units(self) -> int:
        """What the kernel actually streams: width x km (pad lanes and
        borrowed lanes stream the geometry's km blocks regardless of
        their own side size)."""
        return self.width * self.km

    @property
    def mixed(self) -> bool:
        """True when lanes from more than one km bucket share the
        launch (a super-batch in the strict sense)."""
        return len(set(self.kms)) > 1

    @property
    def borrowed(self) -> int:
        """Lanes whose own km is below the launch geometry."""
        return sum(1 for k in self.kms if k < self.km)

    @property
    def n_jobs(self) -> int:
        """Distinct jobs sharing the launch (1 for untagged plans)."""
        return len(set(self.jobs)) if self.jobs else 1

    @property
    def cross_job(self) -> bool:
        """True when lanes from more than one JOB share the launch —
        the fusion broker's headline event."""
        return self.n_jobs > 1


def plan_launches(pools: Dict[int, Sequence[int]], cap: Callable[[int], int],
                  lane: int,
                  overhead: int = LAUNCH_OVERHEAD_UNITS,
                  job_of: Optional[Callable[[int], int]] = None,
                  record: bool = True,
                  part: Optional[int] = None) -> List[Launch]:
    """Pack per-km candidate pools into pow2 super-batch launches.

    Args:
      pools: ``{km: [candidate indices]}`` — km keys must be pow2.
      cap: per-GEOMETRY width ceiling (the jnp evaluator narrows 1/km;
        the kernel path is flat at the engine chunk).  Floored to
        ``lane`` and rounded down to pow2.
      lane: minimum launch width (the kernel's C_LANES out tile; 32 for
        the jnp path — keeps the compiled-width ladder log-sized).
      overhead: per-launch fixed cost in traffic units (see module
        docstring).
      job_of: optional candidate-index -> job-tag map.  When given,
        every emitted launch carries per-lane ``jobs`` tags (parallel to
        ``rows``) — the cross-job fusion broker pools candidates from
        several concurrent mines and demuxes readbacks by this tag.
      record: False for EXPLORATORY plans (the fusion broker plans both
        the fused and the per-job alternative before choosing) — the
        planner metrics/trace event must count only plans that actually
        dispatch, so the caller records the chosen plan via
        :func:`record_plan`.
      part: equivalence-class partition tag stamped on every emitted
        launch (parallel/partition.py) — partitioned mines plan each
        partition's pools separately (their candidate sets are disjoint
        by class), and the tag keys per-partition dispatch accounting.

    Returns launches in dispatch order: full same-km launches largest km
    first, then the merged tails.  Every input candidate appears in
    exactly one launch, exactly once.

    Split rule, per pool: while the remainder exceeds the geometry cap,
    emit cap-width 100%-fill launches; once it fits, emit a single
    padded launch IF the pad is cheaper than another dispatch
    (``(width - n) * km <= overhead``), else peel the largest pow2 as a
    full launch and re-test.  With the full-scale overhead (~512 units)
    this reproduces the measured-best greedy pow2 split; with a
    dryrun-scale overhead (lanes are ~free) it collapses each pool to
    ceil(n / cap) launches.  At most one non-full piece (the TAIL)
    survives per pool; tails then merge across km pools.
    """
    launches: List[Launch] = []
    tails: List[Tuple[int, List[int]]] = []
    for km in sorted(pools, reverse=True):
        rows = list(pools[km])
        if not rows:
            continue
        cap_km = max(lane, floor_pow2(max(1, int(cap(km)))))
        i = 0
        while True:
            n = len(rows) - i
            if n == 0:
                break
            width = max(lane, next_pow2(n))
            if n <= cap_km and (width - n) * km <= overhead:
                tails.append((km, rows[i:]))
                break
            take = min(cap_km, floor_pow2(n))
            if take < lane:
                # sub-lane remainder with a tiny overhead budget: a
                # padded lane-width tail is the only legal shape
                tails.append((km, rows[i:]))
                break
            piece = rows[i:i + take]
            launches.append(Launch(
                km, take, piece, [km] * take,
                [job_of(r) for r in piece] if job_of else None, part))
            i += take

    # cross-km tail merge, largest geometry first: bounds every lane's
    # own km by the geometry, so -1 slots (the pad row) absorb the
    # difference — the generalization of per-bucket pad borrowing
    cur: Tuple[int, List[int], List[int]] | None = None  # (km_geom, rows, kms)
    for km, rows in tails:
        if cur is not None:
            km_g, crows, ckms = cur
            cap_g = max(lane, floor_pow2(max(1, int(cap(km_g)))))
            merged_n = len(crows) + len(rows)
            if merged_n <= cap_g:
                w_cur = max(lane, next_pow2(len(crows)))
                w_merged = max(lane, next_pow2(merged_n))
                w_sep = max(lane, next_pow2(len(rows)))
                if w_merged * km_g <= w_cur * km_g + w_sep * km + overhead:
                    crows.extend(rows)
                    ckms.extend([km] * len(rows))
                    cur = (km_g, crows, ckms)
                    continue
            launches.append(_emit(cur, lane, job_of, part))
        cur = (km, list(rows), [km] * len(rows))
    if cur is not None:
        launches.append(_emit(cur, lane, job_of, part))
    if record:
        record_plan(launches)
    return launches


def record_plan(launches: List[Launch]) -> None:
    """Planner metrics + the per-dispatch trace event for a plan that
    WILL dispatch (``plan_launches`` does this itself unless the caller
    opted into exploratory planning with ``record=False``)."""
    if not launches:
        return
    mixed = sum(1 for L in launches if L.mixed)
    _PLAN_LAUNCHES.inc(len(launches))
    if mixed:
        _PLAN_SUPERBATCHES.inc(mixed)
    # the plan itself is a flight-recorder event (one per dispatch):
    # the per-launch spans the engines open cite geometries, this
    # cites the packer's whole decision
    obs.trace_event(
        "plan_launches",
        candidates=sum(len(L.rows) for L in launches),
        launches=len(launches), superbatches=mixed,
        traffic_units=sum(L.traffic_units for L in launches))


def _emit(cur: Tuple[int, List[int], List[int]], lane: int,
          job_of: Optional[Callable[[int], int]] = None,
          part: Optional[int] = None) -> Launch:
    km_g, rows, kms = cur
    return Launch(km_g, max(lane, next_pow2(len(rows))), rows, kms,
                  [job_of(r) for r in rows] if job_of else None, part)


def superbatch_geometries(lane: int, hi_width: int,
                          kms: Sequence[int] = KM_LADDER
                          ) -> List[Tuple[int, int]]:
    """The finite (km, width) set :func:`plan_launches` can emit for a
    given lane floor and width ceiling — the enumeration the prewarm
    driver walks so no live mine pays a fresh eval compile
    (utils/shapes.py spells the matching ``tsr-eval`` keys)."""
    out = []
    for km in kms:
        w = max(1, int(lane))
        hi = max(w, next_pow2(max(1, int(hi_width))))
        while w <= hi:
            out.append((int(km), w))
            w *= 2
    return out


def late_wave_nb(nb: int, tile: int, ratio: int = 8) -> int:
    """Late-wave geometry for the queue engine: the narrow wave width
    the mine switches to once the live frontier drops below it — many
    underfilled ``nb``-wide waves merge into well-filled narrow ones
    (the wave-axis analog of tail merging).  ``tile``-aligned so
    ``2 * nb_late`` still tiles the pair kernel's parent axis; returns
    ``nb`` unchanged (ladder disabled) when the ratio floor reaches it.
    """
    nb = int(nb)
    cand = max(32, nb // int(ratio))
    cand = -(-cand // int(tile)) * int(tile)
    return min(nb, cand)


class XYStager:
    """Per-geometry xy staging with explicit buffer lifetime.

    The TSR dispatch loop packs the NEXT launch's [width, 2, km] int32
    candidate array while earlier launches are still in flight, so the
    staging buffers are DONATED to each dispatch and only recycled once
    the dispatch's readback has resolved: :meth:`take` hands out a
    free-listed (or fresh) buffer, the engine's eval handle carries it,
    and :meth:`release` returns it after the blocking readback proves
    the compute consumed its inputs.  A fixed round-robin (ping-pong)
    would NOT be safe — the CPU backend aliases numpy memory instead of
    copying at dispatch (observed: reused buffers under a 3-deep
    pipeline read back garbage supports), and the pipeline depth times
    launches-per-dispatch is unbounded.  Buffers a faulted handle holds
    are never released (the device may still reference them); they fall
    to the GC with the handle.
    """

    _POOL_CAP = 8  # free buffers kept per geometry

    def __init__(self):
        self._free: Dict[Tuple[int, int], List[np.ndarray]] = {}

    def take(self, launch: Launch, cands) -> np.ndarray:
        key = (launch.km, launch.width)
        pool = self._free.get(key)
        buf = (pool.pop() if pool
               else np.empty((launch.width, 2, launch.km), np.int32))
        buf.fill(-1)
        for j, r in enumerate(launch.rows):
            x, y = cands[r]
            buf[j, 0, :len(x)] = x
            buf[j, 1, :len(y)] = y
        return buf

    def release(self, bufs) -> None:
        for buf in bufs:
            key = (int(buf.shape[2]), int(buf.shape[0]))
            pool = self._free.setdefault(key, [])
            if len(pool) < self._POOL_CAP:
                pool.append(buf)
