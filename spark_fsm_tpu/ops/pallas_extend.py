"""Fused extension-count-prune Pallas kernel (ISSUE 16).

``pair_supports`` (ops/pallas_support.py) computes the full pair-support
matrix and writes EVERY candidate's count back to HBM; the host (or a
follow-up device op) then compares against the threshold — but at depth
most candidates die at that compare, so most of the result write and all
of the separate threshold pass is wasted motion.  The
"Accelerator-Oriented Algorithm Transformation" thread (PAPERS.md)
argues the prune belongs INSIDE the kernel; the PR 7 resident loop
already moved the compare on-device, this moves it into the kernel
epilogue itself:

- same matmul-style grid as the pair kernel — (P/P_T, NI/I_T, S/S_B),
  sequence-block innermost, out tile accumulating in VMEM;
- on the LAST sequence block the epilogue applies the threshold while
  the tile is still in VMEM: surviving lanes keep their count, dying
  lanes are zeroed (``minsup >= 1`` always, so 0 can never read as a
  survivor), and a PACKED survivor mask (1 bit per lane, LSB-first,
  same packing as ``bitops_jax.pack_seq_bits``) is emitted alongside;
- the mask is 1/32 the int32 matrix — a consumer that walks the mask
  first touches only survivor lanes of the support matrix, so dead
  candidates cost one mask bit of readback instead of a 4-byte count.

The threshold rides in SMEM (a (1, 1) scalar block) so one compiled
kernel serves every wave of a mine — the rising-threshold engines
(resident TSR loop, SPAM's monotone bound) re-launch with a new scalar,
never a new program.

Semantics note (the diffset tie-in, ops/spam_bitops.py): the dEclat
formulation ``support(child) = support(parent_row) - |diffset|`` is an
exact integer identity for every s/i-extension (the child row is the
parent row AND the item row, so its alive-set is a subset), so the
kernel's direct count IS the diffset-formulated count — the jnp
reference (:func:`extend_count_prune_jnp`) computes both and selects
per parent row to pin that identity byte-for-byte in the parity suites.

Mesh caveat: in-kernel pruning is only correct where the kernel sees the
WHOLE sequence axis.  Under ``shard_map`` each device holds partial
counts that must ``psum`` BEFORE the compare, so the sharded wave path
(ops/spam_bitops.py) runs the raw pair kernel per shard and applies
threshold+pack post-psum in the same jitted program — still on device,
one launch, just not inside the kernel epilogue.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops.pallas_support import (
    I_TILE, P_TILE, S_BLOCK, effective_tiles, seq_block)

# Inner-loop cost per uint32 word element matches the pair kernel (AND,
# nonzero, cast, accumulate); the fused epilogue adds O(P*NI) compare/
# select/pack work once per out tile — amortized over S/s_block grid
# steps it is noise against the O(P*NI*S*W) stream, which is why fusing
# the prune is ~free device time and pure readback savings.
EXTEND_VPU_OPS_PER_WORD = 4
EPILOGUE_VPU_OPS_PER_LANE = 6  # compare, select, cast, shift-mul, add, pack


def grid_model(P: int, n_item_rows: int, W: int, S: int, *,
               s_block: Optional[int] = None,
               p_tile: Optional[int] = None,
               i_tile: Optional[int] = None,
               items_rows: Optional[int] = None) -> dict:
    """Grid/traffic/compute model for ONE ``extend_count_prune`` launch —
    the single definition shared with bench_kernels.py (same contract as
    ``pallas_support.grid_model``).  Differences from the pair model:
    the out traffic adds the packed mask (NI/32 uint32 per parent row)
    and the VPU count adds the per-tile prune epilogue."""
    sb = s_block if s_block else seq_block(W)
    ni128 = -(-n_item_rows // 128) * 128
    if items_rows is None:
        items_rows = ni128
    if p_tile is None or i_tile is None:
        ap, ai = effective_tiles(P, n_item_rows, W, items_rows)
        p_tile = ap if p_tile is None else p_tile
        i_tile = ai if i_tile is None else i_tile
    ni = -(-n_item_rows // i_tile) * i_tile
    steps = (P // p_tile) * (ni // i_tile) * (S // sb)
    out_bytes = 4 * P * ni + 4 * P * (ni // 32)
    model_bytes = P * ni * S * W * 4 * (1 / i_tile + 1 / p_tile) + out_bytes
    return {
        "p_tile": int(p_tile), "i_tile": int(i_tile), "s_block": int(sb),
        "grid_steps": int(steps),
        "model_bytes": int(model_bytes),
        "min_useful_bytes": int((P + ni) * S * W * 4 + out_bytes),
        "vpu_ops": int(EXTEND_VPU_OPS_PER_WORD * P * ni * S * W
                       + EPILOGUE_VPU_OPS_PER_LANE * P * ni),
    }


def _prune_epilogue(out_ref, mask_ref, thr_ref, p_tile: int, i_tile: int):
    """Shared last-seq-block epilogue: threshold the accumulated counts
    in VMEM, zero the dead lanes, pack the survivor bits LSB-first
    (identical packing to ``bitops_jax.pack_seq_bits`` over the item
    axis — pinned in tests/test_pallas_extend.py)."""
    thr = thr_ref[0, 0]
    raw = out_ref[:]                                   # [P_T, I_T] int32
    alive = raw >= thr
    out_ref[:] = jnp.where(alive, raw, 0)
    bits = alive.reshape(p_tile, i_tile // 32, 32).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jax.lax.broadcasted_iota(
        jnp.uint32, (p_tile, i_tile // 32, 32), 2))
    mask_ref[:] = jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def _make_extend_kernel_1w(p_tile: int, i_tile: int, n_sb: int):
    """Single-word fast path (2-D blocks; see the pair kernel's 1w note:
    the degenerate [*, 1, S] 3-D block shape compiles ~15x slower in
    Mosaic for identical throughput)."""

    def kernel(thr_ref, pt_ref, items_ref, out_ref, mask_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)
            mask_ref[:] = jnp.zeros_like(mask_ref)

        items = items_ref[:]                           # [I_T, S_B]
        acc = []
        for p in range(p_tile):                        # static unroll
            row = pt_ref[p, :]                         # [S_B]
            hit = ((row[None, :] & items) != 0).astype(jnp.int32)
            acc.append(jnp.sum(hit, axis=-1))          # [I_T]
        out_ref[:] += jnp.stack(acc)                   # [P_T, I_T]

        @pl.when(pl.program_id(2) == n_sb - 1)
        def _():
            _prune_epilogue(out_ref, mask_ref, thr_ref, p_tile, i_tile)

    return kernel


def _make_extend_kernel(p_tile: int, i_tile: int, n_sb: int):
    """Multiword variant: OR the per-word hits before counting (any word
    nonzero -> the sequence contains the join), then the same fused
    threshold+pack epilogue on the last sequence block."""

    def kernel(thr_ref, pt_ref, items_ref, out_ref, mask_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)
            mask_ref[:] = jnp.zeros_like(mask_ref)

        n_words = items_ref.shape[1]
        acc = []
        for p in range(p_tile):                        # static unroll
            hit = None
            for w in range(n_words):                   # static unroll
                row = pt_ref[p, w, :]                  # [S_B]
                h = (row[None, :] & items_ref[:, w, :]) != 0
                hit = h if hit is None else (hit | h)
            acc.append(jnp.sum(hit.astype(jnp.int32), axis=-1))
        out_ref[:] += jnp.stack(acc)

        @pl.when(pl.program_id(2) == n_sb - 1)
        def _():
            _prune_epilogue(out_ref, mask_ref, thr_ref, p_tile, i_tile)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "n_item_rows", "s_block", "p_tile", "i_tile", "interpret"))
def extend_count_prune(pt: jax.Array, items: jax.Array, thr: jax.Array,
                       n_item_rows: int, *, s_block: int = S_BLOCK,
                       p_tile: Optional[int] = None,
                       i_tile: Optional[int] = None,
                       interpret: bool = False):
    """Fused s/i-extension join + support count + threshold prune.

    Args:
      pt: [P, W, S] uint32 parent rows in kernel layout (plain rows read
        by i-extensions, ``sext_transform``-ed rows by s-extensions —
        the caller interleaves them exactly as for ``pair_supports``).
      items: [T, W, S] uint32 item rows in kernel layout.
      thr: int32 threshold, any of shape (), (1,) or (1, 1) — becomes
        the (1, 1) SMEM scalar block.  A TRACED value: one compiled
        kernel serves every threshold.
      n_item_rows: leading item rows to evaluate (rounded up to i_tile).

    Returns:
      (sup [P, NI] int32, mask [P, NI // 32] uint32) with NI =
      n_item_rows rounded up to i_tile.  ``sup`` holds the exact count
      where it is >= thr and EXACTLY 0 otherwise (thr >= 1 always —
      ``abs_minsup`` floors at 1 — so 0 is unambiguous); ``mask`` bit
      ``i % 32`` of word ``i // 32`` is set iff lane ``i`` survived.
    """
    P, W, S = pt.shape
    if p_tile is None or i_tile is None:
        ap, ai = effective_tiles(P, n_item_rows, W, items.shape[0])
        p_tile = ap if p_tile is None else p_tile
        i_tile = ai if i_tile is None else i_tile
    assert P % p_tile == 0, (P, p_tile)
    assert S % s_block == 0, (S, s_block)
    assert i_tile % 128 == 0, i_tile
    assert items.shape[1] == W, (items.shape, W)
    ni = -(-n_item_rows // i_tile) * i_tile
    assert ni <= items.shape[0], (ni, items.shape)
    n_sb = S // s_block
    grid = (P // p_tile, ni // i_tile, n_sb)
    thr2 = jnp.asarray(thr, jnp.int32).reshape(1, 1)
    thr_spec = pl.BlockSpec((1, 1), lambda p, i, sb: (0, 0),
                            memory_space=pltpu.SMEM)
    out_specs = [
        pl.BlockSpec((p_tile, i_tile), lambda p, i, sb: (p, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((p_tile, i_tile // 32), lambda p, i, sb: (p, i),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((P, ni), jnp.int32),
        jax.ShapeDtypeStruct((P, ni // 32), jnp.uint32),
    ]
    if W == 1:  # 2-D fast path
        return pl.pallas_call(
            _make_extend_kernel_1w(p_tile, i_tile, n_sb),
            grid=grid,
            in_specs=[
                thr_spec,
                pl.BlockSpec((p_tile, s_block), lambda p, i, sb: (p, sb),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((i_tile, s_block), lambda p, i, sb: (i, sb),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(thr2, pt[:, 0, :], items[:, 0, :])
    return pl.pallas_call(
        _make_extend_kernel(p_tile, i_tile, n_sb),
        grid=grid,
        in_specs=[
            thr_spec,
            pl.BlockSpec((p_tile, W, s_block), lambda p, i, sb: (p, 0, sb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((i_tile, W, s_block), lambda p, i, sb: (i, 0, sb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(thr2, pt, items)


def extend_count_prune_jnp(p3: jax.Array, items3: jax.Array, thr,
                           use_diff) -> tuple:
    """The jnp reference semantics of the fused kernel — full
    materialization, so use it at TEST/SMOKE scale; the production CPU
    path is the TILED spelling in ``spam_bitops.wave_extend_prune_fn``
    (same math, bounded live intermediate).

    Args:
      p3: [P, S, W] uint32 parent rows (engine-native layout).
      items3: [NI, S, W] uint32 item rows.
      thr: int threshold (>= 1).
      use_diff: [P] bool — rows evaluated via the dEclat diffset
        formulation ``support(parent_row) - |diffset|`` instead of the
        direct count.  The two are an exact integer identity (the child
        alive-set is a subset of the parent row's), so this selects
        between provably-equal spellings — which is precisely what the
        parity suites pin.

    Returns:
      (sup [P, NI] int32 zeroed below thr, mask [P, ceil(NI/32)] uint32
      packed survivor bits) — byte-identical to the kernel outputs.
    """
    joined = p3[:, None] & items3[None]                 # [P, NI, S, W]
    child_alive = B.contains_bits(joined)               # [P, NI, S]
    direct = B.alive_popcount(child_alive)              # [P, NI]
    parent_alive = B.contains_bits(p3)                  # [P, S]
    parent_pop = B.alive_popcount(parent_alive)         # [P]
    diff = B.support_from_diffset(
        parent_pop[:, None],
        B.diffset_count(parent_alive[:, None], child_alive))
    sup = jnp.where(jnp.asarray(use_diff)[:, None], diff, direct)
    alive = sup >= jnp.asarray(thr, jnp.int32)
    return jnp.where(alive, sup, 0), B.pack_seq_bits(alive)
