"""Pallas TPU kernel for the SPADE pair-support matrix (the hot loop).

The reference's hot loop joins each equivalence-class member with each
candidate item and counts supports (SURVEY.md sec 3.1).  The jnp path
gathers two bitmap rows per candidate; XLA's gather lowering reaches only
~10% of HBM bandwidth on TPU, and reads every row once per candidate.

This kernel instead computes the FULL pair matrix ``out[p, i] =
support(pt[p] & items[i])`` with matmul-style 2-D tiling on the VPU:

- grid (P/P_T, NI/I_T, S/S_B), sequence-block innermost so each out tile
  accumulates in VMEM across sequence blocks;
- a parent-row block is re-read once per ITEM TILE (not once per item) and
  an item-row block once per PARENT TILE, so HBM traffic drops by the tile
  factor (~16x) versus per-candidate gathers — the DFS extracts the
  candidate subset of the matrix on device afterwards;
- item rows are slots 0..n_items-1 of the engine's bitmap store, which are
  CONTIGUOUS, so the kernel needs no gather at all.

Single-word fast path: with n_words == 1 (sequences <= 32 itemsets — the
common clickstream shape), a sequence's id-list slice is one uint32 lane,
so "any bit set per sequence" is just ``word != 0`` and support is a lane
count.  Multi-word databases use the jnp fallback path in the engine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes obey the TPU (sublane, lane) = (8, 128) layout: the out block
# [P_TILE, I_TILE] puts item tiles on lanes, so I_TILE must be a multiple
# of 128; S_BLOCK is the lane width of the streamed bitmap blocks.
P_TILE = 16
I_TILE = 128
S_BLOCK = 4096


def _pair_support_kernel(pt_ref, items_ref, out_ref):
    """out[p_tile, i_tile] += lane-count of (pt[p] & items[i]) != 0."""

    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    items = items_ref[:]                            # [I_T, S_B]
    acc = []
    for p in range(P_TILE):                         # static unroll
        row = pt_ref[p, :]                          # [S_B]
        hit = ((row[None, :] & items) != 0).astype(jnp.int32)
        acc.append(jnp.sum(hit, axis=-1))           # [I_T]
    out_ref[:] += jnp.stack(acc)                    # [P_T, I_T]


@functools.partial(jax.jit, static_argnames=("n_item_rows", "interpret"))
def pair_supports(pt: jax.Array, store: jax.Array, n_item_rows: int,
                  *, interpret: bool = False) -> jax.Array:
    """Pair-support matrix between parent rows and item rows.

    Args:
      pt: [P, S] uint32 — gathered (plain, s-ext-transformed) parent rows;
        P must be a multiple of P_TILE, S a multiple of S_BLOCK.
      store: [T, S] uint32 bitmap store; rows 0..n_item_rows-1 are the item
        id-lists (single-word layout, n_words == 1).
      n_item_rows: number of leading store rows to pair against (rounded up
        to I_TILE internally; callers index out[:, :n_items]).

    Returns:
      [P, NI] int32 supports, NI = n_item_rows rounded up to I_TILE.
    """
    P, S = pt.shape
    assert P % P_TILE == 0 and S % S_BLOCK == 0, (P, S)
    ni = -(-n_item_rows // I_TILE) * I_TILE
    assert ni <= store.shape[0], (ni, store.shape)
    grid = (P // P_TILE, ni // I_TILE, S // S_BLOCK)
    return pl.pallas_call(
        _pair_support_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((P_TILE, S_BLOCK), lambda p, i, sb: (p, sb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((I_TILE, S_BLOCK), lambda p, i, sb: (i, sb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((P_TILE, I_TILE), lambda p, i, sb: (p, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P, ni), jnp.int32),
        interpret=interpret,
    )(pt, store)


@functools.partial(jax.jit, static_argnames=("n_item_rows", "interpret"))
def batch_supports(pt: jax.Array, store: jax.Array, n_item_rows: int,
                   pref: jax.Array, item: jax.Array,
                   *, interpret: bool = False) -> jax.Array:
    """Pair matrix + on-device candidate extraction in one dispatch.

    ``pref``/``item`` index (parent-or-transform row, item row) per
    candidate; returns [n_candidates] int32 supports.  Extracting on device
    keeps the host readback at 4 bytes/candidate instead of the full
    matrix.  Accepts [*, S, 1] single-word inputs (squeezed here, inside
    jit, so no eager copy happens on the dispatch path).
    """
    if pt.ndim == 3:
        pt = pt[..., 0]
    if store.ndim == 3:
        store = store[..., 0]
    p = pt.shape[0]
    p_pad = -(-p // P_TILE) * P_TILE  # any batch size: pad rows to the tile
    if p_pad != p:
        pt = jnp.pad(pt, ((0, p_pad - p), (0, 0)))
    out = pair_supports(pt, store, n_item_rows, interpret=interpret)
    return out[pref, item]
