"""Pallas TPU kernel for the SPADE pair-support matrix (the hot loop).

The reference's hot loop joins each equivalence-class member with each
candidate item and counts supports (SURVEY.md sec 3.1).  The jnp path
gathers two bitmap rows per candidate; XLA's gather lowering reaches only
~10% of HBM bandwidth on TPU, and reads every row once per candidate.

This kernel instead computes the FULL pair matrix ``out[p, i] =
support(pt[p] & items[i])`` with matmul-style 2-D tiling on the VPU:

- grid (P/P_T, NI/I_T, S/S_B), sequence-block innermost so each out tile
  accumulates in VMEM across sequence blocks;
- a parent-row block is re-read once per ITEM TILE (not once per item) and
  an item-row block once per PARENT TILE, so HBM traffic drops by the tile
  factor (~16x) versus per-candidate gathers — the DFS extracts the
  candidate subset of the matrix on device afterwards;
- item rows are slots 0..n_items-1 of the engine's bitmap store, which are
  CONTIGUOUS, so the kernel needs no gather at all.

Kernel operand layout is ``[row, word, seq]`` — the word axis is a STATIC
inner loop (per word: AND + nonzero; OR across words; lane-count once), so
a multiword database (> 32 itemsets/sequence) costs W passes over the same
lanes with the count still exact per sequence.  The engine's store layout
is ``[row, seq, word]``; for W == 1 the two layouts are the same bytes (a
free reshape — the store feeds the kernel with no copy), for W > 1 the
engine transposes the item rows ONCE per mine (items never change) and the
per-batch parent rows per call (small).

Sequence blocks shard naturally: under ``shard_map`` each device runs the
kernel over its local seq-axis shard and the engine ``psum``s the partial
supports over ICI (SURVEY.md sec 2.2), identical to the jnp path.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes obey the TPU (sublane, lane) = (8, 128) layout: the out block
# [P_TILE, I_TILE] puts item tiles on lanes, so I_TILE must be a multiple
# of 128; the seq-block (lane width of the streamed bitmap blocks) shrinks
# with the word count so VMEM residency stays ~constant.  The defaults are
# measured, not load-bearing: the tile sweep in KERNELS.json (`python
# bench_kernels.py`, amortized-fence walls) covers (p_tile, i_tile) and
# s_block neighbors at the headline [2048x384x78k] geometry — the kernel
# is VPU-compute-bound there, so tile choice moves the wall only within
# session noise; trust the committed artifact over any remembered number.
P_TILE = 16
I_TILE = 128
S_BLOCK = 4096


def seq_block(n_words: int) -> int:
    """Lane width per grid step for a given word count (multiple of 128)."""
    return max(128, (S_BLOCK // max(1, n_words)) // 128 * 128)


def effective_tiles(P: int, n_item_rows: int, W: int,
                    items_rows: int) -> tuple:
    """The (p_tile, i_tile) the kernel's adaptive default actually runs
    at a given geometry — the ONE definition shared by ``pair_supports``
    and the roofline bench's traffic model (a diverging inline copy
    would make the bench describe tiles the measured program never ran).

    i_tile=384 cuts the parent-block re-read term 3x (1/384 vs 1/128 of
    the P*NI*S traffic) and the grid steps with it — measured 51.6 ms ->
    44.3 ms at the headline geometry (KERNELS.json tile sweep).
    Widening is only taken when it changes NO shapes: the 128-rounded
    item count already divides 384.  W > 1 keeps i_tile=128: a 384-row
    item block is ~6.3 MB in VMEM and the multiword variant is unswept
    on hardware.

    p_tile: 32 where the wide-i_tile conditions hold AND P divides it —
    the measured-best sweep point (32,384 -> 43.35 ms vs 44.59 ms at
    the old (16,384) default, KERNELS.json tile_sweep) and it halves
    the grid steps.  The historical objection was COMPILE time, not
    throughput: the kernel body statically unrolls p_tile rows, so
    p_tile=32 compiles ~4x slower per shape (~15 s), which once
    multiplied into 10+ s mid-push stalls across the incremental
    miner's sweep programs (config-5 regression, caught 2026-07-31).
    The AOT prewarm subsystem (service/prewarm.py) now pays per-shape
    compiles at boot, which flips that trade — but RE-MEASURE before
    trusting the promotion on new hardware (``python bench_kernels.py``
    refreshes KERNELS.json, whose tile_sweep is the evidence this
    default cites), and ``SPARKFSM_PAIR_P_TILE=16`` pins the old tile
    for deployments that cannot prewarm (the re-measure guard)."""
    ni128 = -(-n_item_rows // 128) * 128
    i_tile = (384 if W == 1 and ni128 % 384 == 0 and ni128 <= items_rows
              else I_TILE)
    p_tile = P_TILE
    if i_tile == 384 and P % 32 == 0:
        p_tile = 32
    pin = os.environ.get("SPARKFSM_PAIR_P_TILE")
    if pin:
        try:
            pin = int(pin)
            if pin > 0 and P % pin == 0:
                p_tile = pin
        except ValueError:
            pass
    return p_tile, i_tile


def grid_model(P: int, n_item_rows: int, W: int, S: int, *,
               s_block: Optional[int] = None,
               p_tile: Optional[int] = None,
               i_tile: Optional[int] = None,
               items_rows: Optional[int] = None) -> dict:
    """Grid/dispatch-overhead counters for ONE ``pair_supports`` launch —
    the single definition shared by the KERNELS.json remeasure harness
    (bench_kernels.py) and anything attributing kernel wall to grid
    overhead, so the modeled program can never drift from the measured
    one (tiles resolve through the SAME ``effective_tiles`` the kernel
    uses, including the ``SPARKFSM_PAIR_P_TILE`` re-measure guard).

    Returns the resolved tiles, the grid-step count (each step pays a
    fixed Mosaic prologue + block-DMA turnaround — the measurable
    dispatch-overhead term of the roofline decomposition), the BlockSpec
    HBM traffic model, the minimum-useful bytes, and the VPU op count
    (the compute-roofline term)."""
    sb = s_block if s_block else seq_block(W)
    ni128 = -(-n_item_rows // 128) * 128
    if items_rows is None:
        items_rows = ni128
    if p_tile is None or i_tile is None:
        ap, ai = effective_tiles(P, n_item_rows, W, items_rows)
        p_tile = ap if p_tile is None else p_tile
        i_tile = ai if i_tile is None else i_tile
    ni = -(-n_item_rows // i_tile) * i_tile
    steps = (P // p_tile) * (ni // i_tile) * (S // sb)
    # a parent block re-reads once per item tile, an item block once per
    # parent tile; out written once
    model_bytes = P * ni * S * W * 4 * (1 / i_tile + 1 / p_tile) + 4 * P * ni
    return {
        "p_tile": int(p_tile), "i_tile": int(i_tile), "s_block": int(sb),
        "grid_steps": int(steps),
        "model_bytes": int(model_bytes),
        "min_useful_bytes": int((P + ni) * S * W * 4 + 4 * P * ni),
        "vpu_ops": int(PAIR_VPU_OPS_PER_WORD * P * ni * S * W),
    }


# pair kernel inner loop, per uint32 word element: AND, nonzero compare,
# int32 cast, lane accumulate — the minimum op sequence the semantics
# need on a VPU with no fused popcount-accumulate over masks.  (Shared
# with bench_kernels' compute-roofline model via grid_model above.)
PAIR_VPU_OPS_PER_WORD = 4


def _make_pair_kernel_1w(p_tile: int):
    """Single-word fast path: 2-D blocks.  Kept separate from the general
    kernel because the degenerate [*, 1, S] block shape compiles ~15x
    slower in Mosaic (measured ~420s vs ~25s full-engine cold start) for
    identical steady-state throughput."""

    def kernel(pt_ref, items_ref, out_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        items = items_ref[:]                        # [I_T, S_B]
        acc = []
        for p in range(p_tile):                     # static unroll
            row = pt_ref[p, :]                      # [S_B]
            hit = ((row[None, :] & items) != 0).astype(jnp.int32)
            acc.append(jnp.sum(hit, axis=-1))       # [I_T]
        out_ref[:] += jnp.stack(acc)                # [P_T, I_T]

    return kernel


def _make_pair_kernel(p_tile: int):
    """out[p_tile, i_tile] += #seqs with any word of (pt[p] & items[i]) != 0."""

    def kernel(pt_ref, items_ref, out_ref):
        @pl.when(pl.program_id(2) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        n_words = items_ref.shape[1]
        acc = []
        for p in range(p_tile):                     # static unroll
            hit = None
            for w in range(n_words):                # static unroll
                row = pt_ref[p, w, :]               # [S_B]
                h = (row[None, :] & items_ref[:, w, :]) != 0
                hit = h if hit is None else (hit | h)  # any word -> contains
            acc.append(jnp.sum(hit.astype(jnp.int32), axis=-1))  # [I_T]
        out_ref[:] += jnp.stack(acc)                # [P_T, I_T]

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "n_item_rows", "s_block", "p_tile", "i_tile", "interpret"))
def pair_supports(pt: jax.Array, items: jax.Array, n_item_rows: int,
                  *, s_block: int = S_BLOCK, p_tile: Optional[int] = None,
                  i_tile: Optional[int] = None,
                  interpret: bool = False) -> jax.Array:
    """Pair-support matrix between parent rows and item rows.

    Args:
      pt: [P, W, S] uint32 — (plain, s-ext-transformed) parent rows in
        kernel layout; P must be a multiple of p_tile, S of s_block.
      items: [T, W, S] uint32 item id-lists in kernel layout; rows
        0..n_item_rows-1 are paired against.
      n_item_rows: number of leading item rows to pair against (rounded up
        to i_tile internally; callers index out[:, :n_items]).
      p_tile/i_tile: tile overrides (bench_kernels sweeps them; engines
        use the measured defaults — i_tile must stay a multiple of the
        128-lane tile).

    Returns:
      [P, NI] int32 supports, NI = n_item_rows rounded up to i_tile.
    """
    P, W, S = pt.shape
    # None = the kernel's adaptive default (see effective_tiles); an
    # EXPLICIT p_tile/i_tile (the bench sweep) is honored verbatim
    if p_tile is None or i_tile is None:
        ap, ai = effective_tiles(P, n_item_rows, W, items.shape[0])
        p_tile = ap if p_tile is None else p_tile
        i_tile = ai if i_tile is None else i_tile
    assert P % p_tile == 0, (P, p_tile)
    assert S % s_block == 0, (S, s_block)
    assert i_tile % 128 == 0, i_tile
    assert items.shape[1] == W, (items.shape, W)
    ni = -(-n_item_rows // i_tile) * i_tile
    assert ni <= items.shape[0], (ni, items.shape)
    grid = (P // p_tile, ni // i_tile, S // s_block)
    out_specs = pl.BlockSpec((p_tile, i_tile), lambda p, i, sb: (p, i),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((P, ni), jnp.int32)
    if W == 1:  # 2-D fast path (see _make_pair_kernel_1w)
        return pl.pallas_call(
            _make_pair_kernel_1w(p_tile),
            grid=grid,
            in_specs=[
                pl.BlockSpec((p_tile, s_block), lambda p, i, sb: (p, sb),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((i_tile, s_block), lambda p, i, sb: (i, sb),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(pt[:, 0, :], items[:, 0, :])
    return pl.pallas_call(
        _make_pair_kernel(p_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((p_tile, W, s_block), lambda p, i, sb: (p, 0, sb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((i_tile, W, s_block), lambda p, i, sb: (i, 0, sb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(pt, items)


@functools.partial(jax.jit, static_argnames=(
    "n_item_rows", "items_kernel_layout", "s_block", "interpret", "n_words"))
def batch_supports(pt: jax.Array, items: jax.Array, n_item_rows: int,
                   pref: jax.Array, item: jax.Array,
                   *, items_kernel_layout: bool = False,
                   s_block: int = S_BLOCK, interpret: bool = False,
                   n_words: int = 1) -> jax.Array:
    """Pair matrix + on-device candidate extraction in one dispatch.

    ``pref``/``item`` index (parent-or-transform row, item row) per
    candidate; returns [n_candidates] int32 supports.  Extracting on device
    keeps the host readback at 4 bytes/candidate instead of the full
    matrix.

    ``pt`` arrives in the engine's native [P, S, W] layout or FLAT
    [P, S*W] (word minor; ``n_words`` splits it — the engine keeps its
    store flat across jit boundaries to avoid XLA layout copies) and is
    transposed here, inside jit — a free reshape when W == 1, a small
    per-batch copy otherwise.  ``items`` is the engine store ([T, S, W] /
    flat, same rule) or, with ``items_kernel_layout=True``, a
    pre-transposed [T, W, S] item block (W > 1: transposing the full
    store per call would copy it, so the engine does it once per mine).
    """
    if pt.ndim == 2:
        pt = pt.reshape(pt.shape[0], -1, n_words)
    pt = jnp.transpose(pt, (0, 2, 1))               # [P, W, S]
    if items.ndim == 2:
        items = items.reshape(items.shape[0], -1, n_words)
    if not items_kernel_layout:
        items = jnp.transpose(items, (0, 2, 1))     # free iff W == 1
    p = pt.shape[0]
    p_pad = -(-p // P_TILE) * P_TILE  # any batch size: pad rows to the tile
    if p_pad != p:
        pt = jnp.pad(pt, ((0, p_pad - p), (0, 0), (0, 0)))
    out = pair_supports(pt, items, n_item_rows,
                        s_block=s_block, interpret=interpret)
    return out[pref, item]
