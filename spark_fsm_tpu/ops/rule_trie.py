"""Device-resident packed rule trie + batched prefix->consequent scoring.

The read half of the reference service (PAPER.md §0: clients POST
train AND track/get) queries mined rules for next-item prediction.  At
read QPS the host-side rule walk (service/actors.Questor) is the wrong
shape: every request re-deserializes and re-scans the whole rule list.
This module compiles a completed mine's rule set ONCE into a packed
prefix trie resident in device memory, then scores whole WAVES of
observed prefixes against it in a single fixed-shape launch — the
RDD-Eclat observation (PAPERS.md) that a compiled vertical structure
amortizes best when reused across many queries, applied to serving.

Layout (all planes HBM-resident, pow2-padded so the compile is per
geometry bucket, never per rule set):

- ``ante_tok [F, D]`` int32 — one row per rule LANE (a lane is one
  (rule, consequent-item) pair), the rule's antecedent itemset padded
  with ``-1`` to the D token slots.  Pad lanes carry a ``-2`` sentinel
  that can never match an observed item, so they are dead without a
  separate mask plane.
- CSR trie structure — unique antecedents are deduplicated into a
  prefix trie (``trie_child_off/trie_child_tok/trie_child_node``,
  child offsets CSR-style; ``trie_lane_off/trie_lane_ids`` attach
  lanes to their terminal node).  The flat lane planes above are the
  trie unrolled for the wave kernel; the CSR planes are the compact
  spelling (shared-prefix compression is reported in ``stats``).
- ``lane_item / lane_sup / lane_supx [F]`` int32 — consequent id +
  confidence/support planes.  Confidence stays the exact integer pair
  ``(sup, supx)`` end to end (utils/canonical keeps rule text
  float-free for the same reason); the float division happens on the
  host at response time, byte-identical to the Questor oracle's.
- ``sel_rank / score_rank / lane_of_rank [F]`` int32 — the oracle's
  ENTIRE comparison semantics, precomputed at compile time with the
  oracle's own arithmetic (Python float confidence, stable payload
  order).  ``sel_rank`` is the unique per-lane rank by (conf desc,
  sup desc, payload order) — the per-item winner is the matched lane
  with the smallest ``sel_rank``.  ``score_rank`` is the DENSE rank by
  (conf desc, sup desc) — equal pairs share a rank so the cross-item
  tie-break falls through to item id, exactly the oracle's
  ``(-conf, -sup, item)`` sort key.  The device kernel therefore does
  only int32 comparisons: no float op exists that could diverge.

Scoring (``_score_fn``, one jitted program per ``predict:f{F}d{D}w{W}
m{M}`` geometry — utils/shapes key, prewarmed like every other launch
ladder): masked AND-fold of each lane's antecedent tokens over the
wave's observed-prefix token lanes (the engines' evaluator idiom —
models/tsr._eval_kernel folds candidate item rows the same way),
scatter-min per consequent slot to pick each item's winning lane, then
a stable int32 argsort for the top-m emit.  Rows are independent:
fusing W requests into one wave cannot change any row's bytes (the
positional-disjointness argument service/fusion.py already relies on,
made trivial here by the kernel being integer-only).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import PatternResult, RuleResult, sort_patterns

_PAD = -1          # unused antecedent token slot (matches vacuously)
_DEAD = -2         # pad-lane sentinel (matches nothing)
_BIG = np.int32(1 << 30)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


# ---------------------------------------------------------------------------
# Host reference — the Questor prediction semantics, verbatim
# ---------------------------------------------------------------------------

def predict_host(rules: Sequence[RuleResult], prefix: Sequence[int],
                 m: int) -> List[dict]:
    """Brute-force prefix -> top-m consequent scoring over the raw rule
    list — the byte-parity reference for the device trie (and the exact
    semantics service/actors.Questor serves on ``/get/prediction``)."""
    have = set(int(i) for i in prefix)
    best: Dict[int, tuple] = {}
    for x, y, sup, supx in rules:
        if supx <= 0 or not set(x) <= have:
            continue
        conf = sup / supx
        for it in y:
            if it in have:
                continue
            cur = best.get(it)
            if cur is None or (conf, sup) > (cur[0], cur[1]):
                best[it] = (conf, sup, supx, x, y)
    ranked = sorted(best.items(), key=lambda kv: (-kv[1][0], -kv[1][1], kv[0]))
    return [
        {"item": it, "confidence": conf, "support": sup,
         "antecedent_support": supx, "antecedent": list(x),
         "consequent": list(y)}
        for it, (conf, sup, supx, x, y) in ranked[:max(0, int(m))]
    ]


def rules_from_patterns(patterns: Sequence[PatternResult]) -> List[RuleResult]:
    """Derive prediction rules from a frequent-SEQUENCE set (the SPADE/
    SPAM engines emit patterns, not rules): for every pattern with >= 2
    itemsets, antecedent = items of the prefix, consequent = the last
    itemset's new items, supx = the prefix pattern's own support (the
    set is closed under prefixes, so it is present).  Deterministic
    (canonical pattern order) so the oracle and the trie consume the
    same list in the same order."""
    sup_of = {tuple(p): s for p, s in patterns}
    rules: List[RuleResult] = []
    for pat, sup in sort_patterns(patterns):
        if len(pat) < 2:
            continue
        supx = sup_of.get(tuple(pat[:-1]))
        if supx is None or supx <= 0:
            continue
        x = tuple(sorted({i for s in pat[:-1] for i in s}))
        y = tuple(sorted(set(pat[-1]) - set(x)))
        if not y:
            continue
        rules.append((x, y, int(sup), int(supx)))
    return rules


def rules_digest(payload: str) -> str:
    """Content address of a serialized rule set — the artifact cache key
    component that makes re-mine staleness a cache miss, not a bug."""
    return hashlib.sha256(payload.encode()).hexdigest()


# ---------------------------------------------------------------------------
# Artifact compile
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RuleTrie:
    """Compiled artifact: device planes + the host rule list they index."""

    rules: List[RuleResult]            # payload order (oracle order)
    lanes: int                         # real lanes (rule, cons-item) pairs
    F: int                             # pow2 lane axis
    D: int                             # pow2 antecedent/prefix token axis
    digest: str                        # rule-set content digest
    built_ts: float                    # host wall at build (staleness)
    # device planes (jax arrays; see module docstring)
    ante_tok: object = None
    lane_item: object = None
    lane_slot: object = None
    sel_rank: object = None
    lane_of_rank: object = None
    score_rank: object = None
    lane_sup: object = None
    lane_supx: object = None
    # CSR trie planes (device-resident compact spelling)
    trie_child_off: object = None
    trie_child_tok: object = None
    trie_child_node: object = None
    trie_lane_off: object = None
    trie_lane_ids: object = None
    # host mirrors for response decode
    h_lane_rule: Optional[np.ndarray] = None
    h_lane_item: Optional[np.ndarray] = None
    stats: Optional[dict] = None

    def nbytes(self) -> int:
        total = 0
        for f in ("ante_tok", "lane_item", "lane_slot", "sel_rank",
                  "lane_of_rank", "score_rank", "lane_sup", "lane_supx",
                  "trie_child_off", "trie_child_tok", "trie_child_node",
                  "trie_lane_off", "trie_lane_ids"):
            arr = getattr(self, f)
            if arr is not None:
                total += int(np.asarray(arr).nbytes)
        return total


def _build_csr(antes: List[Tuple[int, ...]],
               lane_ante: List[int]) -> dict:
    """Prefix trie over the unique antecedent token sequences; children
    CSR-packed per node, lanes attached to their terminal node."""
    children: List[Dict[int, int]] = [{}]
    node_of_ante: List[int] = []
    for ante in antes:
        node = 0
        for t in ante:
            nxt = children[node].get(t)
            if nxt is None:
                nxt = len(children)
                children[node][t] = nxt
                children.append({})
            node = nxt
        node_of_ante.append(node)
    n = len(children)
    child_off = np.zeros(n + 1, np.int32)
    toks: List[int] = []
    kids: List[int] = []
    for i, ch in enumerate(children):
        for t in sorted(ch):
            toks.append(t)
            kids.append(ch[t])
        child_off[i + 1] = len(toks)
    lanes_at: List[List[int]] = [[] for _ in range(n)]
    for lane, ai in enumerate(lane_ante):
        lanes_at[node_of_ante[ai]].append(lane)
    lane_off = np.zeros(n + 1, np.int32)
    lane_ids: List[int] = []
    for i, ls in enumerate(lanes_at):
        lane_ids.extend(ls)
        lane_off[i + 1] = len(lane_ids)
    return {
        "child_off": child_off,
        "child_tok": np.asarray(toks or [0], np.int32),
        "child_node": np.asarray(kids or [0], np.int32),
        "lane_off": lane_off,
        "lane_ids": np.asarray(lane_ids or [0], np.int32),
        "n_nodes": n,
        "token_slots": sum(len(a) for a in antes),
    }


def build_trie(rules: Sequence[RuleResult], *, lanes_floor: int = 0,
               depth_floor: int = 0, device_put: bool = True) -> RuleTrie:
    """Compile a rule list into the packed trie artifact.

    ``lanes_floor``/``depth_floor`` pad the geometry UP to the declared
    prewarm envelope so a live artifact lands on an already-compiled
    ``predict:*`` key (the stream_seq_floor idea applied to serving).
    """
    import time as _time

    rules = [(tuple(int(i) for i in x), tuple(int(i) for i in y),
              int(sup), int(supx))
             for x, y, sup, supx in rules if int(supx) > 0]
    # lanes in payload order: rule r, consequent item y[j]
    lane_rule: List[int] = []
    lane_item: List[int] = []
    antes: List[Tuple[int, ...]] = []
    ante_ix: Dict[Tuple[int, ...], int] = {}
    lane_ante: List[int] = []
    for r, (x, y, sup, supx) in enumerate(rules):
        ai = ante_ix.get(x)
        if ai is None:
            ai = ante_ix[x] = len(antes)
            antes.append(x)
        for it in y:
            lane_rule.append(r)
            lane_item.append(it)
            lane_ante.append(ai)
    L = len(lane_rule)
    depth = max([len(x) for x, *_ in rules], default=0)
    F = _next_pow2(max(L, lanes_floor, 1))
    D = _next_pow2(max(depth, depth_floor, 1))

    # the oracle's comparison semantics, precomputed with the oracle's
    # own arithmetic: conf is a PYTHON float (sup/supx) so float64
    # collisions tie exactly where the Questor walk ties
    conf = [rules[lane_rule[i]][2] / rules[lane_rule[i]][3]
            for i in range(L)]
    sups = [rules[lane_rule[i]][2] for i in range(L)]
    order = sorted(range(L), key=lambda i: (-conf[i], -sups[i], i))
    sel_rank = np.arange(F, dtype=np.int32)
    lane_of_rank = np.arange(F, dtype=np.int32)
    for rank, lane in enumerate(order):
        sel_rank[lane] = rank
        lane_of_rank[rank] = lane
    score_rank = np.full(F, _BIG, np.int32)
    rank = -1
    prev = None
    for r_pos, lane in enumerate(order):
        key = (conf[lane], sups[lane])
        if key != prev:
            rank = r_pos  # dense-enough: equal pairs share, order holds
            prev = key
        score_rank[lane] = rank

    # dense consequent slots sorted by item id (slot asc == item asc,
    # the oracle's final tie-break axis)
    slot_items = sorted(set(lane_item))
    slot_of = {it: s for s, it in enumerate(slot_items)}

    ante_tok = np.full((F, D), _PAD, np.int32)
    ante_tok[L:, 0] = _DEAD
    l_item = np.full(F, -3, np.int32)
    l_slot = np.zeros(F, np.int32)
    l_sup = np.zeros(F, np.int32)
    l_supx = np.zeros(F, np.int32)
    for i in range(L):
        x = rules[lane_rule[i]][0]
        ante_tok[i, :len(x)] = x
        l_item[i] = lane_item[i]
        l_slot[i] = slot_of[lane_item[i]]
        l_sup[i] = rules[lane_rule[i]][2]
        l_supx[i] = rules[lane_rule[i]][3]

    csr = _build_csr(antes, lane_ante)
    digest = hashlib.sha256(repr(rules).encode()).hexdigest()
    art = RuleTrie(
        rules=rules, lanes=L, F=F, D=D, digest=digest,
        built_ts=_time.time(),
        h_lane_rule=np.asarray(lane_rule or [0], np.int32),
        h_lane_item=np.asarray(l_item),
        stats={
            "rules": len(rules), "lanes": L, "F": F, "D": D,
            "consequent_slots": len(slot_items),
            "trie_nodes": csr["n_nodes"],
            # shared-prefix compression: token slots the trie stores
            # once vs the flat per-antecedent total
            "token_slots_flat": csr["token_slots"],
            "token_slots_trie": max(0, csr["n_nodes"] - 1),
        })
    planes = {
        "ante_tok": ante_tok, "lane_item": l_item, "lane_slot": l_slot,
        "sel_rank": sel_rank, "lane_of_rank": lane_of_rank,
        "score_rank": score_rank, "lane_sup": l_sup, "lane_supx": l_supx,
        "trie_child_off": csr["child_off"],
        "trie_child_tok": csr["child_tok"],
        "trie_child_node": csr["child_node"],
        "trie_lane_off": csr["lane_off"],
        "trie_lane_ids": csr["lane_ids"],
    }
    if device_put:
        import jax

        planes = {k: jax.device_put(v) for k, v in planes.items()}
    for k, v in planes.items():
        setattr(art, k, v)
    return art


# ---------------------------------------------------------------------------
# Scoring kernel (jnp reference; one compile per geometry bucket)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _score_fn(F: int, D: int, W: int, M: int):
    import jax
    import jax.numpy as jnp

    def body(ante_tok, lane_item, lane_slot, sel_rank, lane_of_rank,
             score_rank, lane_sup, lane_supx, q_tok):
        # masked AND-fold: every antecedent token slot is either pad or
        # a member of the row's observed-prefix token lanes
        member = (ante_tok[None, :, :, None]
                  == q_tok[:, None, None, :]).any(-1)       # [W, F, D]
        ok = (ante_tok[None, :, :] == _PAD) | member
        matched = ok.all(-1)                                 # [W, F]
        # the oracle never predicts an already-observed item
        seen = (lane_item[None, :, None] == q_tok[:, None, :]).any(-1)
        matched = matched & ~seen
        key = jnp.where(matched, sel_rank[None, :], _BIG)
        w_ix = jnp.arange(W, dtype=jnp.int32)[:, None]
        slots = jnp.broadcast_to(lane_slot[None, :], (W, F))
        best = jnp.full((W, F), _BIG, jnp.int32).at[
            w_ix, slots].min(key)                            # per-slot winner
        valid = best < _BIG
        win = lane_of_rank[jnp.minimum(best, F - 1)]         # [W, F]
        order_key = jnp.where(valid, score_rank[win], _BIG)
        # stable argsort == (score_rank asc, slot asc) == the oracle's
        # (-conf, -sup, item) — slots are item-ascending by construction
        order = jnp.argsort(order_key, axis=-1)[:, :M]
        top_valid = jnp.take_along_axis(valid, order, axis=-1)
        top_lane = jnp.take_along_axis(win, order, axis=-1)
        top_lane = jnp.where(top_valid, top_lane, -1)
        safe = jnp.maximum(top_lane, 0)
        top_sup = jnp.where(top_valid, lane_sup[safe], -1)
        top_supx = jnp.where(top_valid, lane_supx[safe], -1)
        return top_lane, top_sup, top_supx

    return jax.jit(body)


def warm_geometry(F: int, D: int, W: int, M: int) -> str:
    """Compile (and record) the scoring program for one geometry bucket
    with zero planes — the prewarm driver's entry point."""
    import jax.numpy as jnp

    fn = _score_fn(F, D, W, M)
    z = jnp.zeros((F, D), jnp.int32) + _DEAD
    v = jnp.zeros((F,), jnp.int32)
    q = jnp.full((W, D), _PAD, jnp.int32)
    out = fn(z, v - 3, v, jnp.arange(F, dtype=jnp.int32),
             jnp.arange(F, dtype=jnp.int32), v + _BIG, v, v, q)
    out[0].block_until_ready()
    key = shapes.key_predict(F, D, W, M)
    shapes.record(key)
    return key


def score_wave(trie: RuleTrie, prefixes: Sequence[Sequence[int]],
               m: int, *, wave_pad: int = 0) -> List[List[dict]]:
    """Score a wave of observed prefixes; returns per-request top-m
    entry lists in the Questor response spelling (host float division
    over the winning lanes' exact integer pairs)."""
    n = len(prefixes)
    W = _next_pow2(max(n, wave_pad, 1))
    M = _next_pow2(max(int(m), 1))
    for p in prefixes:
        if len(p) > trie.D:
            raise ValueError(
                f"observed prefix length {len(p)} exceeds trie depth "
                f"{trie.D}; rebuild the artifact at a deeper geometry")
    q = np.full((W, trie.D), _PAD, np.int32)
    for i, p in enumerate(prefixes):
        if p:
            q[i, :len(p)] = np.asarray(list(p), np.int32)
    fn = _score_fn(trie.F, trie.D, W, M)
    top_lane, top_sup, top_supx = fn(
        trie.ante_tok, trie.lane_item, trie.lane_slot, trie.sel_rank,
        trie.lane_of_rank, trie.score_rank, trie.lane_sup, trie.lane_supx,
        np.ascontiguousarray(q))
    shapes.record(shapes.key_predict(trie.F, trie.D, W, M))
    top_lane = np.asarray(top_lane)
    top_sup = np.asarray(top_sup)
    top_supx = np.asarray(top_supx)
    out: List[List[dict]] = []
    for i in range(n):
        entries: List[dict] = []
        # the kernel's argsort slice yields min(M, F) columns — a top-m
        # pad wider than the lane axis cannot produce more winners than
        # there are lanes
        for j in range(min(int(m), M, top_lane.shape[1])):
            lane = int(top_lane[i, j])
            if lane < 0:
                break
            x, y, sup, supx = trie.rules[int(trie.h_lane_rule[lane])]
            # the support planes rode the launch — cross-check the
            # device's winner against the host rule it indexes
            if int(top_sup[i, j]) != sup or int(top_supx[i, j]) != supx:
                raise AssertionError(
                    f"device support planes disagree with host rules at "
                    f"lane {lane}: {(int(top_sup[i, j]), int(top_supx[i, j]))}"
                    f" != {(sup, supx)}")
            entries.append({
                "item": int(trie.h_lane_item[lane]),
                "confidence": sup / supx,
                "support": sup,
                "antecedent_support": supx,
                "antecedent": list(x),
                "consequent": list(y),
            })
        out.append(entries)
    return out
