"""Pallas TPU kernel for TSR rule-support evaluation (the 2nd hot loop).

A TSR candidate rule X => Y evaluates as (SURVEY.md sec 2.4; models/tsr.py
module docstring): A = AND over x in X of prefix-or rows, C = AND over
y in Y of suffix-or rows, sup = #seqs with (shift_up_one(A) & C) != 0 and
supx = #seqs with A != 0.

The jnp path gathers every candidate's rows into [chunk, S, W] temps —
~4 live copies per launch — which caps the launch width at ~512
candidates on a 990k-sequence DB (15G HBM) and makes full-scale mines
dispatch-latency-bound (5k+ launches x ~55ms tunnel RTT).  This kernel
streams the sequence axis through VMEM instead:

- grid (C, S/S_B), sequence-block innermost; each step DMAs the 2*km
  candidate rows' current seq block straight from the prep stores (NO
  [C, S] materialization anywhere), folds the ANDs, applies the
  multiword shift_up_one carry chain, and accumulates the two counts
  into the out block — per-launch HBM temp is O(1), so the launch width
  is bounded by dispatch cost alone (8192 default).
- row selection is dynamic via scalar-prefetched candidate indices
  (PrefetchScalarGridSpec): in_spec j's index_map reads xy[c, side, j];
  unused slots (-1, sides shorter than the km bucket) map to the pad row
  M (all ones — the AND identity), which the caller appends to the prep
  stores (models/tsr.py _kernel_layout_fn builds it once per round).
- out[2, C] accumulates (sup, supx) per candidate: the block is a
  [2, 128] lane tile revisited for 128 consecutive candidates x all seq
  blocks; a broadcasted-iota mask adds each step's two scalars into its
  candidate's lane.

Operand layout: the seq axis is FOLDED to 2-D (sublane, lane) tiles —
``[M+1, S/128, 128]`` single-word, ``[M+1, W, S/128, 128]`` multiword —
because Mosaic requires the last two block dims to be (divisible by 8,
divisible by 128): a flat ``(1, S_B)`` row block fails lowering on real
hardware (the row index must live on a LEADING dim, where any block size
is legal).  The word axis is a static inner loop with exact cross-word
carries, mirroring ops/bitops_jax.shift_up_one.

Under shard_map each device runs the kernel on its seq-axis shard and the
engine psums the partial counts (identical to the jnp path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_LANES = 128     # candidates per out block (lane width)
LANE = 128        # folded seq-axis minor dim
# Worst-case live row blocks per step.  The scoped-VMEM limit is 16M on
# v5e and the row blocks are not its only occupant (out block, prefetch,
# pipeline overheads) — a 16M budget compiled to 17.86M of scoped vmem
# and was rejected; 12M leaves the observed ~2M of headroom.
_VMEM_BUDGET = 12 << 20


def seq_block(n_words: int, s_local: int) -> int:
    """Seq lanes per grid step — as LARGE as the VMEM budget allows (the
    whole shard when it fits).  The grid has C x S/s_block steps and the
    per-step work is tiny, so small blocks make launches per-step-
    overhead-bound: measured on v5e, 4096-lane blocks ran a 99k-seq
    8192-candidate launch ~10x slower than the same launch at one
    whole-shard block.  Budget: 2*km row refs of [n_words, sb/128, 128]
    uint32, double-buffered, at the worst km=4 bucket.  Always a multiple
    of 8*128 (the folded sublane x lane tile)."""
    cap = _VMEM_BUDGET // (2 * 4 * 2 * 4 * max(1, n_words))  # lanes
    cap = max(8 * LANE, cap // (8 * LANE) * (8 * LANE))
    n_blocks = max(1, -(-s_local // cap))
    per = -(-s_local // n_blocks)
    return max(8 * LANE, -(-per // (8 * LANE)) * (8 * LANE))


def _mask_add(out_ref, c, sup, supx):
    """Accumulate this candidate's two counts into its lane of the
    [2, C_LANES] out block."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (2, C_LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (2, C_LANES), 0)
    val = jnp.where(row == 0, sup, supx)
    out_ref[:] += jnp.where(lane == (c % C_LANES), val, 0)


def _make_kernel_1w(km: int):
    def kernel(xy_ref, *refs):
        # refs: km prefix blocks, km suffix blocks ([1, sb/128, 128]), out
        p_refs, s_refs, out_ref = refs[:km], refs[km:2 * km], refs[2 * km]
        c, sb = pl.program_id(0), pl.program_id(1)

        @pl.when(((c % C_LANES) == 0) & (sb == 0))
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        a = p_refs[0][0]                            # [sb/128, 128]
        for j in range(1, km):
            a = a & p_refs[j][0]
        cc = s_refs[0][0]
        for j in range(1, km):
            cc = cc & s_refs[j][0]
        # single word: shift toward higher positions, carry-in 0
        shifted = a << jnp.uint32(1)
        sup = jnp.sum(((shifted & cc) != 0).astype(jnp.int32))
        supx = jnp.sum((a != 0).astype(jnp.int32))
        _mask_add(out_ref, c, sup, supx)

    return kernel


def _make_kernel(km: int, n_words: int):
    def kernel(xy_ref, *refs):
        p_refs, s_refs, out_ref = refs[:km], refs[km:2 * km], refs[2 * km]
        c, sb = pl.program_id(0), pl.program_id(1)

        @pl.when(((c % C_LANES) == 0) & (sb == 0))
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        hit = None     # any word of (shift_up_one(A) & C) != 0
        hitx = None    # any word of A != 0
        carry = None   # shift_up_one cross-word carry (bit 31 -> next word)
        for w in range(n_words):   # static unroll, words low -> high
            a = p_refs[0][0, w]                     # [sb/128, 128]
            for j in range(1, km):
                a = a & p_refs[j][0, w]
            cc = s_refs[0][0, w]
            for j in range(1, km):
                cc = cc & s_refs[j][0, w]
            shifted = a << jnp.uint32(1)
            if carry is not None:
                shifted = shifted | carry
            carry = a >> jnp.uint32(31)
            h = (shifted & cc) != 0
            hx = a != 0
            hit = h if hit is None else (hit | h)
            hitx = hx if hitx is None else (hitx | hx)
        sup = jnp.sum(hit.astype(jnp.int32))
        supx = jnp.sum(hitx.astype(jnp.int32))
        _mask_add(out_ref, c, sup, supx)

    return kernel


@functools.partial(jax.jit, static_argnames=("km", "s_block", "interpret"))
def rule_supports(p1: jax.Array, s1: jax.Array, xy: jax.Array, *,
                  km: int, s_block: int = 0,
                  interpret: bool = False) -> jax.Array:
    """(sup, supx) for a batch of candidate rules.

    Args:
      p1: prefix-or-incl item rows in FOLDED kernel layout —
        [M+1, S/128, 128] uint32 single-word, [M+1, W, S/128, 128]
        multiword — with row M = ALL ONES (the AND identity for unused
        slots).  S must be a multiple of ``s_block``.
      s1: suffix-or-incl rows, same shape/convention.
      xy: [C, 2, km] int32 — row indices (side 0 = X, 1 = Y); -1 = unused
        slot.  C must be a multiple of 128.
      km: side-size bucket (static).

    Returns:
      [2, C] int32 — row 0 = sup(X=>Y), row 1 = sup(X).
    """
    single = p1.ndim == 3
    W = 1 if single else p1.shape[1]
    S = p1.shape[-2] * LANE
    M = p1.shape[0] - 1   # pad row index
    C = xy.shape[0]
    sb = s_block or seq_block(W, S)
    assert S % sb == 0 and C % C_LANES == 0, (S, sb, C)
    assert p1.shape[-1] == LANE, p1.shape
    assert xy.shape[1:] == (2, km), (xy.shape, km)
    sb_rows = sb // LANE

    # The prefetched candidate indices live in SMEM, which pads the MINOR
    # dim of multi-D arrays to the 128-lane tile (a [C, 2, km] array
    # became an 8 MB "prefetched SMEM operand" against a 1 MB budget on
    # v5e) — so they ride FLAT: xy_flat[(c*2 + side)*km + j].
    xy_flat = xy.reshape(-1)

    def row(side, j):
        # -1 (unused slot) -> the all-ones pad row
        def index_map(c, s, xy_ref):
            r = xy_ref[(c * 2 + side) * km + j]
            r = jnp.where(r < 0, M, r)
            return (r, s, 0) if single else (r, 0, s, 0)
        shape = ((1, sb_rows, LANE) if single
                 else (1, W, sb_rows, LANE))
        return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(C, S // sb),
        in_specs=([row(0, j) for j in range(km)]
                  + [row(1, j) for j in range(km)]),
        out_specs=pl.BlockSpec((2, C_LANES), lambda c, s, xy_ref:
                               (0, c // C_LANES),
                               memory_space=pltpu.VMEM),
    )
    kernel = _make_kernel_1w(km) if single else _make_kernel(km, W)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((2, C), jnp.int32),
        interpret=interpret,
    )(xy_flat, *([p1] * km + [s1] * km))
