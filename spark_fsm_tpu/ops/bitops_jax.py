"""JAX bitmap primitives — bit-exact mirrors of ops/bitops_np.py.

All ops are word-wise VPU work (uint32 bitwise + popcount): the SPADE
temporal join is memory-bandwidth-bound, so the goal is fusion (XLA fuses
the transform/AND/any/sum chain into one pass over HBM) rather than MXU use.
The word axis is the last (minor, lane) axis; the unrolled word loop in
``sext_transform`` is static so everything stays inside one fused kernel.

Semantics (SURVEY.md sec 2.3 step 4):
- ``sext_transform``: per sequence, set all bits strictly after the first
  set bit (first-occurrence postfix mask) — carry chain toward higher words;
- ``i_extend``: AND at identical positions;
- ``support``: #sequences with any surviving bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

FULL = jnp.uint32(0xFFFFFFFF)


def prefix_or_word(w: jax.Array) -> jax.Array:
    """Within-word inclusive prefix OR (bit p = OR of bits 0..p)."""
    for shift in (1, 2, 4, 8, 16):
        w = w | (w << jnp.uint32(shift))
    return w


def sext_transform(b: jax.Array) -> jax.Array:
    """First-occurrence postfix mask over the last (word) axis."""
    n_words = b.shape[-1]
    carry = jnp.zeros(b.shape[:-1], dtype=bool)
    outs = []
    for j in range(n_words):
        w = b[..., j]
        outs.append((prefix_or_word(w) << jnp.uint32(1)) | jnp.where(carry, FULL, jnp.uint32(0)))
        carry = carry | (w != 0)
    return jnp.stack(outs, axis=-1)


def prefix_or_incl(b: jax.Array) -> jax.Array:
    """Inclusive prefix OR (bit p = any bit q <= p) — TSR 'X occurred by p'."""
    n_words = b.shape[-1]
    carry = jnp.zeros(b.shape[:-1], dtype=bool)
    outs = []
    for j in range(n_words):
        w = b[..., j]
        outs.append(prefix_or_word(w) | jnp.where(carry, FULL, jnp.uint32(0)))
        carry = carry | (w != 0)
    return jnp.stack(outs, axis=-1)


def suffix_or_word(w: jax.Array) -> jax.Array:
    for shift in (1, 2, 4, 8, 16):
        w = w | (w >> jnp.uint32(shift))
    return w


def suffix_or_incl(b: jax.Array) -> jax.Array:
    """Inclusive suffix OR (bit p = any bit q >= p) — TSR 'Y occurs at >= p'."""
    n_words = b.shape[-1]
    carry = jnp.zeros(b.shape[:-1], dtype=bool)
    outs = []
    for j in range(n_words - 1, -1, -1):
        w = b[..., j]
        outs.append(suffix_or_word(w) | jnp.where(carry, FULL, jnp.uint32(0)))
        carry = carry | (w != 0)
    return jnp.stack(outs[::-1], axis=-1)


def shift_up_one(b: jax.Array) -> jax.Array:
    """Multiword shift toward higher positions by 1 (cross-word carries)."""
    n_words = b.shape[-1]
    carry = jnp.zeros(b.shape[:-1], dtype=jnp.uint32)
    outs = []
    for j in range(n_words):
        w = b[..., j]
        outs.append((w << jnp.uint32(1)) | carry)
        carry = w >> jnp.uint32(31)
    return jnp.stack(outs, axis=-1)


def i_extend(prefix_bitmap: jax.Array, item_bitmap: jax.Array) -> jax.Array:
    return prefix_bitmap & item_bitmap


def s_extend(prefix_bitmap: jax.Array, item_bitmap: jax.Array) -> jax.Array:
    return sext_transform(prefix_bitmap) & item_bitmap


def join(prefix_bitmap: jax.Array, item_bitmap: jax.Array, is_s) -> jax.Array:
    """Temporal join with per-candidate extension type.

    ``is_s`` broadcasts against the leading (candidate) axes: True selects
    s-extension, False i-extension.
    """
    is_s = jnp.asarray(is_s)
    sel = is_s[(...,) + (None,) * (prefix_bitmap.ndim - is_s.ndim)]
    return jnp.where(sel, sext_transform(prefix_bitmap), prefix_bitmap) & item_bitmap


def popcount(w: jax.Array) -> jax.Array:
    """Per-word population count (SWAR), uint32 -> int32 same shape."""
    w = w.astype(jnp.uint32)
    w = w - ((w >> jnp.uint32(1)) & jnp.uint32(0x55555555))
    w = (w & jnp.uint32(0x33333333)) + ((w >> jnp.uint32(2))
                                        & jnp.uint32(0x33333333))
    w = (w + (w >> jnp.uint32(4))) & jnp.uint32(0x0F0F0F0F)
    return ((w * jnp.uint32(0x01010101)) >> jnp.uint32(24)).astype(jnp.int32)


def tail_mask(n_valid: int, n_words: int) -> jax.Array:
    """[n_words] uint32 mask of the valid bits (static shapes; mirrors
    bitops_np.tail_mask — see there for why popcount reductions must
    apply it: ``sext_transform`` saturates tail-word padding bits)."""
    pos = jnp.arange(n_words * 32, dtype=jnp.int32).reshape(n_words, 32)
    bits = (pos < n_valid).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (bits * weights).sum(axis=-1).astype(jnp.uint32)


def masked_popcount(b: jax.Array, n_valid: int) -> jax.Array:
    """[..., n_words] -> [...] int32 set bits at VALID positions only.

    The mask is load-bearing for any bitmap downstream of the SPAM
    s-extension shift: ``sext_transform`` fills every bit above the
    first occurrence, including padding positions past the true
    capacity in the tail word, so the unmasked popcount overcounts
    whenever the bit axis is not a multiple of the word width."""
    return jnp.sum(popcount(b & tail_mask(n_valid, b.shape[-1])),
                   axis=-1, dtype=jnp.int32)


def pack_seq_bits(active: jax.Array) -> jax.Array:
    """Pack boolean [..., n_seq] into LSB-first uint32 words
    [..., ceil(n_seq/32)] with an explicit all-zero tail pad — the
    fixed-shape SPAM support formulation (support = popcount of the
    packed per-sequence alive bits).  Zero-padding is the tail-word
    fix when the sequence count is not a multiple of the word width."""
    n_seq = active.shape[-1]
    n_w = max(1, -(-n_seq // 32))
    pad = n_w * 32 - n_seq
    if pad:
        active = jnp.concatenate(
            [active, jnp.zeros(active.shape[:-1] + (pad,), bool)], axis=-1)
    bits = active.reshape(active.shape[:-1] + (n_w, 32)).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (bits * weights).sum(axis=-1).astype(jnp.uint32)


def support_popcount(bitmap: jax.Array) -> jax.Array:
    """[..., n_seq, n_words] -> [...] int32 support via pack+popcount —
    bit-identical to :func:`support`, pinned against the bitops_np
    reference; the spelling the SPAM wave kernel fuses."""
    packed = pack_seq_bits(contains_bits(bitmap))
    return jnp.sum(popcount(packed), axis=-1, dtype=jnp.int32)


def alive_popcount(alive: jax.Array) -> jax.Array:
    """[..., n_seq] bool -> [...] int32: count of alive sequences via the
    pack+popcount spelling (the SPAM wave's reduction)."""
    return jnp.sum(popcount(pack_seq_bits(alive)), axis=-1, dtype=jnp.int32)


def diffset_count(parent_alive: jax.Array, child_alive: jax.Array) -> jax.Array:
    """dEclat diffset size from per-sequence alive bits: #sequences alive
    in the parent row but dead in the child join, [..., n_seq] bool pair
    -> [...] int32.  Mirrors bitops_np.diffset_count (which takes raw
    bitmaps); the wave kernels already hold the collapsed alive bits, so
    this spelling fuses into the same pass."""
    return alive_popcount(parent_alive & ~child_alive)


def support_from_diffset(parent_support: jax.Array,
                         diffset_size: jax.Array) -> jax.Array:
    """dEclat support identity ``support(parent_row) - |diffset|`` —
    exact because every s/i-extension ANDs the joined-against parent
    row, making the child's alive-set a subset of the parent's."""
    return parent_support - diffset_size


def contains_bits(bitmap: jax.Array) -> jax.Array:
    """[..., n_seq, n_words] -> [..., n_seq] bool: any bit set per sequence."""
    return jnp.any(bitmap != 0, axis=-1)


def support(bitmap: jax.Array) -> jax.Array:
    """[..., n_seq, n_words] -> [...] int32 sequence-count support."""
    return jnp.sum(contains_bits(bitmap), axis=-1, dtype=jnp.int32)
