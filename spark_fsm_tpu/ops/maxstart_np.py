"""NumPy reference ops for constrained (maxgap/maxwindow) SPADE.

Plain SPAM bitmaps only record occurrence END positions, which is enough
for unconstrained containment but not for gap/window checks.  The
constrained state is the *max-start array* M[..., p] (int16):

    M[p] = latest start position over occurrences of the pattern that end
           at position p, or -1 if none.

Why latest start: an occurrence satisfying maxwindow exists iff the one
with the latest start does (span p - M[p] is minimal), and "latest start"
is composable under both extension types:

- i-extension by y:  M'[p] = M[p] if y occurs at p else -1 (same itemset,
  same start);
- s-extension by y with maxgap g:  M'[p] = max_{p-g <= q < p} M[q] if y
  occurs at p else -1 (gap counts between consecutive itemset positions,
  cSPADE semantics; g=None means unbounded);
- support: #sequences with any p where M[p] >= 0 and p - M[p] <= w
  (w=None: no window check).

Single items trivially satisfy both constraints (no gaps, span 0), so the
constrained root state is M0[p] = p where the item occurs.  SURVEY.md
sec 2.3 step 6.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

NONE16 = np.int16(-1)


def expand_bits(words: np.ndarray) -> np.ndarray:
    """Unpack uint32 word bitmaps into a bool position axis.

    [..., n_words] uint32 -> [..., n_words*32] bool, position p = bit p%32
    of word p//32 (the layout of data/vertical.py).
    """
    words = np.asarray(words, dtype=np.uint32)
    shifts = np.arange(32, dtype=np.uint32)
    bits = (words[..., :, None] >> shifts) & np.uint32(1)
    return bits.reshape(*words.shape[:-1], words.shape[-1] * 32).astype(bool)


def root_state(words: np.ndarray) -> np.ndarray:
    """M0 for a single item: its own position where it occurs, else -1."""
    occ = expand_bits(words)
    pos = np.arange(occ.shape[-1], dtype=np.int16)
    return np.where(occ, pos, NONE16)


def prev_max(m: np.ndarray, maxgap: Optional[int]) -> np.ndarray:
    """out[p] = max over q in [p-maxgap, p-1] of m[q] (all q<p if None)."""
    m = np.asarray(m, dtype=np.int16)
    p_axis = m.shape[-1]
    if maxgap is None or maxgap >= p_axis:
        run = np.maximum.accumulate(m, axis=-1)
        out = np.full_like(m, NONE16)
        out[..., 1:] = run[..., :-1]
        return out
    out = np.full_like(m, NONE16)
    for d in range(1, maxgap + 1):
        out[..., d:] = np.maximum(out[..., d:], m[..., :-d])
    return out


def s_extend(m: np.ndarray, item_words: np.ndarray, maxgap: Optional[int]) -> np.ndarray:
    occ = expand_bits(item_words)
    pm = prev_max(m, maxgap)
    return np.where(occ & (pm >= 0), pm, NONE16)


def i_extend(m: np.ndarray, item_words: np.ndarray) -> np.ndarray:
    occ = expand_bits(item_words)
    return np.where(occ & (m >= 0), m, NONE16)


def support(m: np.ndarray, maxwindow: Optional[int]) -> np.ndarray:
    """[..., n_seq, n_pos] -> [...] sequence counts under the window."""
    m = np.asarray(m)
    ok = m >= 0
    if maxwindow is not None:
        pos = np.arange(m.shape[-1], dtype=m.dtype)
        ok &= (pos - m) <= maxwindow
    return np.count_nonzero(ok.any(axis=-1), axis=-1)
