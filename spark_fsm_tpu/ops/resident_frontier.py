"""Resident-frontier TSR: whole km-ladders expanded in ONE dispatch.

BENCH_SCALE config 3d (unlimited ``max_side``, the service default)
degrades into a host-driven expand/readback/re-plan loop at the deep
TSR levels: every few thousand candidates the host blocks on a
readback, re-heaps, re-plans and re-dispatches — 371 launches where the
capped config 3 pays 41, and each launch is pure dispatch latency the
ragged packer (ops/ragged_batch.py) cannot amortize because the NEXT
level's candidates do not exist until the host has seen this level's
supports.  The queue engine (models/spade_queue.py) already proved the
cure for SPADE: keep the frontier in HBM and run the whole expansion
inside a ``lax.while_loop``, reading back only survivors.  This module
ports that architecture to TSR's best-first rule search:

- **the frontier lives in HBM**: a FIFO ring of fixed-capacity entries
  — packed (X, Y) item slots (``exy``, km-ladder capacity ``caps.km``
  per side), the admission bound, the parent support, the EXACT
  antecedent support ``psupx`` (the conf-bound prune input, PR 2), and
  the chain flags.  Entries are the host engine's own sibling-chain
  entries bit-for-bit, so a frontier SPILLS to the host path (and a
  host checkpoint resumes on device) with no translation layer;
- **each wave** pops ``nb`` entries, advances their sibling chains,
  applies the pop-time conf-bound subtree prune, evaluates
  (sup, supx) with the same masked AND-fold as the jnp evaluator,
  appends accepted rules to a packed record buffer, maintains the
  EXACT current top-k support threshold on device (a sorted ``topk``
  buffer — the dynamically rising ``minsup`` no longer needs a host
  round trip), and enqueues the left/right child chain heads at the
  ring tail;
- **wide-then-narrow**: the carry is wave-width-independent (PR 2's
  late-wave trick), so the host switches to the narrow ``nb_late``
  program when the live frontier drains below it — many underfilled
  wide waves become well-filled narrow ones at zero extra dispatches;
- **the km ladder ends in a DEFER buffer, not an abort**: a child that
  needs an item slot past ``caps.km`` is real host work (an unlimited
  side past the compiled ladder), but it is almost never LIVE work —
  by round end the exact top-k threshold has risen past nearly every
  deep candidate's bound.  So over-ladder children are appended to a
  fixed-capacity defer buffer (``km + 1`` item slots — a deferred
  child extends a full-ladder side by exactly one item) and the wave
  continues; at round end the host filters the deferred entries
  against the FINAL minsup and resumes the classic path only for the
  survivors.  On every eval config that is zero entries — the round
  completes entirely on device;
- **capacity is a routing concern, never correctness**: every wave
  pre-checks its ring/record/defer capacity and commits NOTHING on
  overflow — the host reads the intact frontier back and continues on
  the classic ragged-batch path (the overflow-to-host spill protocol).

Parity argument (why the device search returns the host engine's exact
rule set): the final set is {expansion-reachable rules with
conf >= minconf and sup >= s_k}, which models/tsr.py already proves
pop-order independent — acceptance uses exact (sup, supx), the
end-of-round s_k filter is exact, and every prune (bound < minsup,
conf-bound subtree) only discards candidates provably below the final
threshold.  The device loop uses the SAME expansion scheme and only
ever prunes against a minsup that is <= the true current k-th largest
accepted support (the on-device top-k is exact), so it evaluates a
possibly different sub-threshold candidate set but accepts the same
final rules.  FIFO pop order (vs the host's best-first heap) only
changes how fast minsup rises — wasted work at worst, never wrong.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.utils import obs, shapes

# Exact on-device top-k capacity: the ``topk`` buffer is a static shape
# shared by every compiled resident program (k itself is TRACED, so one
# compile serves every request k <= K_PAD; a larger k routes host).
K_PAD = 1024

_SEGMENTS = obs.REGISTRY.counter(
    "fsm_tsr_resident_segments_total",
    "resident-frontier segment dispatches (one compiled while_loop run)")
_WAVES = obs.REGISTRY.counter(
    "fsm_tsr_resident_waves_total",
    "frontier waves executed on device inside resident segments")
_SPILLS = obs.REGISTRY.counter(
    "fsm_tsr_resident_spills_total",
    "resident frontiers spilled back to the host path (capacity overflow)")
_DEFERRED = obs.REGISTRY.counter(
    "fsm_tsr_resident_deferred_total",
    "over-km-ladder children deferred to the host's end-of-round filter")
_HANDOFFS = obs.REGISTRY.counter(
    "fsm_tsr_resident_handoffs_total",
    "rounds whose surviving deferred entries resumed the host path")
_FALLBACKS = obs.REGISTRY.counter(
    "fsm_tsr_resident_fallbacks_total",
    "resident rounds abandoned to the host path after a dispatch fault")
_READBACK = obs.REGISTRY.counter(
    "fsm_tsr_resident_readback_bytes_total",
    "bytes read back from resident device state (records + spills)")


@dataclasses.dataclass(frozen=True)
class ResidentCaps:
    """Static capacities of the resident program (compile-time shapes).

    ``nb``: frontier entries popped per wave; ``nb_late`` the narrow
    late-wave width.  ``ring``: live-frontier capacity (FIFO slot
    reuse, so it bounds ``tail - head``, not the mine's node count).
    ``r_cap``: accepted-rule records for the whole round (append-only;
    the host filters to the final s_k).  ``km``: per-side item-slot
    capacity — the km-ladder depth expanded on device (sides past 4
    are unobserved in every eval config, same reasoning as
    ragged_batch.KM_LADDER); children past the ladder land in the
    DEFER buffer (``d_cap`` entries of ``km + 1`` slots) for the
    host's end-of-round filter instead of aborting the round.
    ``i_max``: host-side total-wave runaway guard."""

    nb: int = 512
    ring: int = 16384
    r_cap: int = 32768
    km: int = 4
    d_cap: int = 4096
    i_max: int = 1 << 20

    @property
    def nb_late(self) -> int:
        return RB.late_wave_nb(self.nb, 32)


def working_set_bytes(caps: ResidentCaps, row_bytes: int, m: int) -> int:
    """Per-device working set of the resident program — shared by
    :func:`caps_for` (sizing) and the engine's eligibility check so the
    two cannot disagree.  Counts the prep pair, the carry-doubled ring
    and record state (a ``while_loop`` carry cannot alias its input on
    the first iteration), and ~6 live [nb, S, W] eval intermediates
    (the masked fold's gather/AND chain)."""
    entry = 2 * caps.km * 4 + 3 * 4 + 2 + 4     # exy + int32x3 + flags
    rec = 2 * caps.km * 4 + 2 * 4               # rec_xy + sup/supx
    defer = 2 * (caps.km + 1) * 4 + 3 * 4 + 2 + 4
    return (2 * m * row_bytes                   # p1/s1 preps
            + 2 * (caps.ring * entry + caps.r_cap * rec
                   + caps.d_cap * defer + K_PAD * 4)
            + 6 * caps.nb * row_bytes)          # wave eval temps


def caps_for(n_seq: int, n_words: int, m: int,
             budget: int) -> Optional[ResidentCaps]:
    """Capacity model: the largest pow2 ring (and a budget-clamped wave
    width) whose working set fits the engine's eval budget; None when
    even the smallest geometry does not fit (the round routes host).
    Deterministic in (n_seq, n_words, m, budget), so the prewarm
    enumerator derives the same caps the engine will construct."""
    row = max(1, n_seq * max(1, n_words) * 4)
    nb = min(512, max(64, RB.floor_pow2(max(1, budget // (8 * row)))))
    # FIFO breadth-first residency needs headroom the host's best-first
    # heap does not: until the top-k threshold starts biting, every
    # popped entry can push up to three chain heads, so the live
    # frontier peaks at roughly a BFS level width.  Start the search at
    # 64k entries (~13 MB of ring state) and shrink to fit the budget.
    ring = 65536
    while ring >= 2048:
        caps = ResidentCaps(nb=nb, ring=ring, r_cap=2 * ring,
                            d_cap=max(1024, ring // 8))
        if working_set_bytes(caps, row, m) <= budget:
            return caps
        ring //= 2
    return None


def resident_keys(n_seq: int, n_words: int, m: int,
                  caps: ResidentCaps) -> List[str]:
    """The shape keys the resident round can compile: the wide program
    and (when distinct) the narrow late-wave program."""
    out = [shapes.key_tsr_resident(n_seq, n_words, m, caps.km, caps.nb,
                                   caps.ring)]
    if caps.nb_late < caps.nb:
        out.append(shapes.key_tsr_resident(n_seq, n_words, m, caps.km,
                                           caps.nb_late, caps.ring))
    return out


# ---------------------------------------------------------------------------
# Host-side frontier packing (entries <-> device carry)
# ---------------------------------------------------------------------------
# Entry tuples use the host engine's queue spelling:
#   (bound, x, y, can_right, side, psup, psupx)
# — the checkpoint "stack" rows of models/tsr.frontier_state with the
# bound kept positive.  One spelling for roots, resumes and spills.


def root_entries(sup_l: Sequence[int], minsup: int, num: int, den: int,
                 max_side: Optional[int]) -> List[tuple]:
    """The round's root chain heads — the device twin of the host
    loop's root ``chain_push`` calls (one side-1 chain per item i over
    partners j != i; items are support-sorted so the first admissible
    partner is index 0, or 1 for item 0)."""
    m = len(sup_l)
    out = []
    for i in range(m):
        c = 1 if i == 0 else 0
        if c >= m:
            continue
        b = min(sup_l[i], sup_l[c])
        if b < minsup:
            continue
        if (max_side is not None and 1 >= max_side and sup_l[i] > 0
                and b * den < sup_l[i] * num):
            continue  # chain_push's side-1 conf kill at max_side=1
        out.append((b, (i,), (c,), True, 1, sup_l[i], sup_l[i]))
    return out


def pack_state(entries: Sequence[tuple],
               results: Sequence[tuple],
               caps: ResidentCaps) -> Optional[dict]:
    """Numpy arrays for a fresh device carry, or None when the frontier
    does not fit the caps (the round then routes host: entry count past
    the ring or defer buffer, a side past the defer width, or too many
    kept results).  Entries whose sides fit the km ladder land in the
    ring; one-past-the-ladder entries (a resumed snapshot that already
    deferred them) land straight in the defer buffer."""
    ring, km, r_cap = caps.ring, caps.km, caps.r_cap
    if len(results) > r_cap:
        return None
    fit = [e for e in entries if len(e[1]) <= km and len(e[2]) <= km]
    over = [e for e in entries if len(e[1]) > km or len(e[2]) > km]
    if len(fit) > ring or len(over) > caps.d_cap:
        return None
    exy = np.full((ring, 2, km), -1, np.int32)
    bound = np.zeros(ring, np.int32)
    psup = np.zeros(ring, np.int32)
    psupx = np.zeros(ring, np.int32)
    cr = np.zeros(ring, bool)
    side = np.zeros(ring, np.int32)
    for q, (b, x, y, crq, sd, ps, px) in enumerate(fit):
        exy[q, 0, :len(x)] = x
        exy[q, 1, :len(y)] = y
        bound[q] = b
        psup[q] = ps
        psupx[q] = px
        cr[q] = bool(crq)
        side[q] = sd
    dxy = np.full((caps.d_cap, 2, km + 1), -1, np.int32)
    dbound = np.zeros(caps.d_cap, np.int32)
    dpsup = np.zeros(caps.d_cap, np.int32)
    dpsupx = np.zeros(caps.d_cap, np.int32)
    dcr = np.zeros(caps.d_cap, bool)
    dside = np.zeros(caps.d_cap, np.int32)
    for q, (b, x, y, crq, sd, ps, px) in enumerate(over):
        if len(x) > km + 1 or len(y) > km + 1:
            return None
        dxy[q, 0, :len(x)] = x
        dxy[q, 1, :len(y)] = y
        dbound[q] = b
        dpsup[q] = ps
        dpsupx[q] = px
        dcr[q] = bool(crq)
        dside[q] = sd
    rec_xy = np.full((r_cap, 2, km), -1, np.int32)
    rec_sup = np.zeros(r_cap, np.int32)
    rec_supx = np.zeros(r_cap, np.int32)
    for r, (sup, supx, x, y) in enumerate(results):
        if len(x) > km or len(y) > km:
            return None
        rec_xy[r, 0, :len(x)] = x
        rec_xy[r, 1, :len(y)] = y
        rec_sup[r] = sup
        rec_supx[r] = supx
    topk = np.zeros(K_PAD, np.int32)
    sups = sorted((int(r[0]) for r in results), reverse=True)[:K_PAD]
    topk[:len(sups)] = sups
    return {"exy": exy, "bound": bound, "psup": psup, "psupx": psupx,
            "cr": cr, "side": side, "rec_xy": rec_xy, "rec_sup": rec_sup,
            "rec_supx": rec_supx, "n_entries": len(fit),
            "n_results": len(results), "topk": topk,
            "dxy": dxy, "dbound": dbound, "dpsup": dpsup,
            "dpsupx": dpsupx, "dcr": dcr, "dside": dside,
            "n_defer": len(over)}


def unpack_entries(exy: np.ndarray, bound: np.ndarray, psup: np.ndarray,
                   psupx: np.ndarray, cr: np.ndarray, side: np.ndarray,
                   head: int, tail: int, minsup: int) -> List[tuple]:
    """Live ring entries back into host queue tuples (the spill path and
    the checkpoint snapshot).  Bound-dead entries (< minsup) are dropped
    exactly like ``frontier_state`` drops them — pop would discard
    them anyway."""
    ring = exy.shape[0]
    out = []
    for qid in range(int(head), int(tail)):
        r = qid % ring
        b = int(bound[r])
        if b < minsup:
            continue
        x = tuple(int(v) for v in exy[r, 0] if v >= 0)
        y = tuple(int(v) for v in exy[r, 1] if v >= 0)
        out.append((b, x, y, bool(cr[r]), int(side[r]), int(psup[r]),
                    int(psupx[r])))
    return out


def unpack_results(rec_xy: np.ndarray, rec_sup: np.ndarray,
                   rec_supx: np.ndarray, n_rec: int,
                   minsup: int) -> List[tuple]:
    """Accepted records back into (sup, supx, x, y) tuples, filtered to
    the current minsup — the host engine's progressive results filter,
    applied once at readback instead of per threshold rise."""
    out = []
    for r in range(int(n_rec)):
        sup = int(rec_sup[r])
        if sup < minsup:
            continue
        x = tuple(int(v) for v in rec_xy[r, 0] if v >= 0)
        y = tuple(int(v) for v in rec_xy[r, 1] if v >= 0)
        out.append((sup, int(rec_supx[r]), x, y))
    return out


# ---------------------------------------------------------------------------
# The compiled segment program
# ---------------------------------------------------------------------------

# Carry layout (width-independent — the wide and narrow programs
# interchange mid-round, PR 2's late-wave contract):
#   0 exy       [ring, 2, km] int32   packed X/Y item slots (-1 pad)
#   1 bound     [ring] int32          admission bound (min over chain)
#   2 psup      [ring] int32          parent's exact support
#   3 psupx     [ring] int32          exact antecedent support (side-1)
#   4 cr        [ring] bool           can_right flag
#   5 side      [ring] int32          0 = grow-X chain, 1 = grow-Y
#   6 head      int32                 FIFO head (monotonic qid)
#   7 tail      int32                 FIFO tail
#   8 rec_xy    [r_cap, 2, km] int32  accepted-rule slots
#   9 rec_sup   [r_cap] int32
#  10 rec_supx  [r_cap] int32
#  11 rec_count int32
#  12 topk      [K_PAD] int32         desc-sorted accepted supports
#  13 n_acc     int32                 accepted rules ever (threshold arm)
#  14 minsup    int32                 current exact top-k threshold
#  15 overflow  bool                  capacity spill flag (wave atomic)
#  16 waves     int32
#  17 evaluated int32
#  18 pruned    int32                 conf-bound subtree prunes
#  19 dxy       [d_cap, 2, km+1]      deferred over-ladder children
#  20 dbound    [d_cap] int32
#  21 dpsup     [d_cap] int32
#  22 dpsupx    [d_cap] int32
#  23 dcr       [d_cap] bool
#  24 dside     [d_cap] int32
#  25 d_count   int32                 deferred entries so far
N_CARRY = 26


@functools.lru_cache(maxsize=32)
def _resident_fn(nb: int, km: int):
    """Compiled resident segment: run at most ``wave_budget`` waves (a
    TRACED argument — one compile serves every segment size) of the
    frontier expansion at wave width ``nb``.  jax.jit caches per input
    shape on top of this, so (m, n_seq, n_words, ring, r_cap) are
    implicit compile keys — exactly the axes of ``key_tsr_resident``.
    The carry is DONATED: unlike the queue engine, no element aliases
    engine-persistent state (the prep pair rides outside the carry), so
    even the first segment donates and the ring never doubles."""
    import jax
    import jax.numpy as jnp

    from spark_fsm_tpu.ops import bitops_jax as B

    FULL = jnp.uint32(0xFFFFFFFF)

    def run(p1, s1, sup_items, num, den, k, max_side_t, wave_end, *carry):
        m = p1.shape[0]
        ring = carry[0].shape[0]
        r_cap = carry[8].shape[0]
        d_cap = carry[19].shape[0]
        i32 = jnp.int32
        lane = jnp.arange(nb, dtype=i32)
        item = jnp.arange(m, dtype=i32)
        pos = jnp.arange(km, dtype=i32)

        def fold(t, idx):
            acc = None
            for j in range(km):
                i = idx[:, j]
                g = jnp.where((i >= 0)[:, None, None],
                              t[jnp.maximum(i, 0)], FULL)
                acc = g if acc is None else acc & g
            return acc

        def body(c):
            (exy, bound, psup, psupx, cr, side, head, tail, rec_xy,
             rec_sup, rec_supx, rec_count, topk, n_acc, minsup, overflow,
             waves, evaluated, pruned,
             dxy, dbound, dpsup, dpsupx, dcr, dside, d_count) = c

            qid = head + lane
            active = qid < tail
            ridx = jnp.where(active, qid % ring, 0)
            ex = exy[ridx]                        # [nb, 2, km]
            b = jnp.where(active, bound[ridx], -1)
            ps = psup[ridx]
            px = psupx[ridx]
            crl = cr[ridx]
            sd = side[ridx]
            live = active & (b >= minsup)   # bound-dead lanes drop whole,
            # like the host's pop_batch queue.clear() at a risen minsup

            xs, ys = ex[:, 0, :], ex[:, 1, :]
            nx = jnp.sum(xs >= 0, axis=1).astype(i32)
            ny = jnp.sum(ys >= 0, axis=1).astype(i32)
            # chain items are appended in ascending order, so the last
            # valid slot is the side's max item
            maxx = jnp.take_along_axis(
                xs, jnp.maximum(nx - 1, 0)[:, None], axis=1)[:, 0]
            maxy = jnp.where(ny > 0, jnp.take_along_axis(
                ys, jnp.maximum(ny - 1, 0)[:, None], axis=1)[:, 0], -1)
            free = ~jnp.any(
                ex[:, :, :, None] == item[None, None, None, :],
                axis=(1, 2))                      # [nb, m] not-in-rule

            # ---- sibling advance (before eval, the host pop order) ----
            lastv = jnp.where(sd == 0, maxx, maxy)
            sib_adm = free & (item[None, :] > lastv[:, None])
            has_sib = jnp.any(sib_adm, axis=1)
            sib_c = jnp.argmax(sib_adm, axis=1).astype(i32)
            sib_b = jnp.minimum(ps, sup_items[sib_c])
            sib_kill = ((sd == 1) & (px > 0) & (sib_b * den < px * num)
                        & (nx >= max_side_t))
            push_sib = live & has_sib & (sib_b >= minsup) & ~sib_kill
            slot_j = jnp.maximum(jnp.where(sd == 0, nx, ny) - 1, 0)
            repl = pos[None, :] == slot_j[:, None]
            sib_x = jnp.where(((sd == 0)[:, None]) & repl,
                              sib_c[:, None], xs)
            sib_y = jnp.where(((sd == 1)[:, None]) & repl,
                              sib_c[:, None], ys)
            sib_ex = jnp.stack([sib_x, sib_y], axis=1)

            # ---- pop-time conf-bound subtree prune (exact host test:
            # side-1, psupx known, bound below the conf floor, and the
            # antecedent can never grow again) ----
            lv_adm = (free & (item[None, :] > maxx[:, None])
                      & (sup_items[None, :] >= minsup))
            left_viable = (nx < max_side_t) & jnp.any(lv_adm, axis=1)
            confdead = (live & (sd == 1) & (px > 0)
                        & (b * den < px * num) & ~left_viable)
            ev = live & ~confdead

            # ---- evaluate: the jnp evaluator's masked AND-fold ----
            a = fold(p1, xs)
            cm = fold(s1, ys)
            sup = jnp.where(ev, B.support(B.shift_up_one(a) & cm), 0)
            supx = jnp.where(ev, B.support(a), 0)

            acc_ok = (ev & (sup >= minsup) & (supx > 0)
                      & (sup * den >= supx * num))
            n_new = jnp.sum(acc_ok, dtype=i32)

            # ---- exact on-device top-k threshold ----
            merged = -jnp.sort(-jnp.concatenate(
                [topk, jnp.where(acc_ok, sup, 0)]))[:K_PAD]
            n_acc2 = n_acc + n_new
            thresh = jnp.take(merged, jnp.maximum(k - 1, 0))
            minsup2 = jnp.maximum(
                minsup, jnp.where(n_acc2 >= k, thresh, 1))

            # ---- children: left/right chain heads (host consume()) ----
            expand = ev & (sup >= minsup)
            l_adm = free & (item[None, :] > maxx[:, None])
            l_has = jnp.any(l_adm, axis=1)
            l_c = jnp.argmax(l_adm, axis=1).astype(i32)
            l_b = jnp.minimum(sup, sup_items[l_c])
            push_l = (expand & (nx < max_side_t) & l_has
                      & (l_b >= minsup2))
            r_adm = free & (item[None, :] > maxy[:, None])
            r_has = jnp.any(r_adm, axis=1)
            r_c = jnp.argmax(r_adm, axis=1).astype(i32)
            r_b = jnp.minimum(sup, sup_items[r_c])
            r_kill = ((supx > 0) & (r_b * den < supx * num)
                      & (nx >= max_side_t))
            push_r = (expand & crl & (ny < max_side_t) & r_has
                      & (r_b >= minsup2) & ~r_kill)
            # km-ladder end: a child that needs a slot past km is real
            # host work (an unlimited side past the compiled ladder) —
            # but almost never LIVE work, so it lands in the DEFER
            # buffer for the host's end-of-round filter instead of
            # aborting the round.  Ring entries hold at most km items
            # per side, so a deferring side is exactly full (n == km).
            defer_l = push_l & (nx >= km)
            defer_r = push_r & (ny >= km)
            push_l = push_l & (nx < km)
            push_r = push_r & (ny < km)
            l_ex = jnp.stack([jnp.where(
                pos[None, :] == jnp.minimum(nx, km - 1)[:, None],
                l_c[:, None], xs), ys], axis=1)
            r_ex = jnp.stack([xs, jnp.where(
                pos[None, :] == jnp.minimum(ny, km - 1)[:, None],
                r_c[:, None], ys)], axis=1)

            # ---- capacity pre-check: commit nothing on overflow ----
            pushes = jnp.concatenate([push_sib, push_l, push_r])
            n_push = jnp.sum(pushes, dtype=i32)
            defers = jnp.concatenate([defer_l, defer_r])
            n_defer = jnp.sum(defers, dtype=i32)
            new_head = jnp.minimum(head + nb, tail)
            new_tail = tail + n_push
            ovf = ((new_tail - new_head > ring)
                   | (rec_count + n_new > r_cap)
                   | (d_count + n_defer > d_cap))

            # ---- records ----
            rpos = rec_count + jnp.cumsum(acc_ok.astype(i32)) - 1
            rw = jnp.where(acc_ok & ~ovf, rpos, r_cap)
            rec_xy = rec_xy.at[rw].set(ex, mode="drop")
            rec_sup = rec_sup.at[rw].set(sup, mode="drop")
            rec_supx = rec_supx.at[rw].set(supx, mode="drop")

            # ---- defer over-ladder children (km + 1 item slots: the
            # deferring side is exactly full, so the new item lands in
            # the one extra slot) ----
            ncol = jnp.full((nb, 1), -1, i32)
            dl_ex = jnp.stack([
                jnp.concatenate([xs, l_c[:, None]], axis=1),
                jnp.concatenate([ys, ncol], axis=1)], axis=1)
            dr_ex = jnp.stack([
                jnp.concatenate([xs, ncol], axis=1),
                jnp.concatenate([ys, r_c[:, None]], axis=1)], axis=1)
            dpos = d_count + jnp.cumsum(defers.astype(i32)) - 1
            dw = jnp.where(defers & ~ovf, dpos, d_cap)
            dxy = dxy.at[dw].set(
                jnp.concatenate([dl_ex, dr_ex]), mode="drop")
            dbound = dbound.at[dw].set(
                jnp.concatenate([l_b, r_b]), mode="drop")
            dpsup = dpsup.at[dw].set(
                jnp.concatenate([sup, sup]), mode="drop")
            dpsupx = dpsupx.at[dw].set(
                jnp.concatenate([jnp.zeros(nb, i32), supx]), mode="drop")
            dcr = dcr.at[dw].set(
                jnp.concatenate([jnp.zeros(nb, bool),
                                 jnp.ones(nb, bool)]), mode="drop")
            dside = dside.at[dw].set(
                jnp.concatenate([jnp.zeros(nb, i32),
                                 jnp.ones(nb, i32)]), mode="drop")

            # ---- enqueue at the ring tail (slots of entries popped
            # THIS wave may be reused — reads precede writes in
            # dataflow order; new_tail - new_head <= ring guarantees no
            # still-live slot is overwritten) ----
            all_ex = jnp.concatenate([sib_ex, l_ex, r_ex])
            all_b = jnp.concatenate([sib_b, l_b, r_b])
            all_ps = jnp.concatenate([ps, sup, sup])
            zero = jnp.zeros(nb, i32)
            all_px = jnp.concatenate(
                [jnp.where(sd == 1, px, 0), zero, supx])
            all_cr = jnp.concatenate(
                [crl, jnp.zeros(nb, bool), jnp.ones(nb, bool)])
            all_sd = jnp.concatenate([sd, zero, jnp.ones(nb, i32)])
            qpos = tail + jnp.cumsum(pushes.astype(i32)) - 1
            qr = jnp.where(pushes & ~ovf, qpos % ring, ring)
            exy = exy.at[qr].set(all_ex, mode="drop")
            bound = bound.at[qr].set(all_b, mode="drop")
            psup = psup.at[qr].set(all_ps, mode="drop")
            psupx = psupx.at[qr].set(all_px, mode="drop")
            cr = cr.at[qr].set(all_cr, mode="drop")
            side = side.at[qr].set(all_sd, mode="drop")

            keep = lambda old, new: jnp.where(ovf, old, new)
            return (exy, bound, psup, psupx, cr, side,
                    keep(head, new_head), keep(tail, new_tail),
                    rec_xy, rec_sup, rec_supx,
                    keep(rec_count, rec_count + n_new),
                    jnp.where(ovf, topk, merged),
                    keep(n_acc, n_acc2), keep(minsup, minsup2),
                    overflow | ovf, waves + keep(0, 1),
                    evaluated + keep(0, jnp.sum(ev, dtype=i32)),
                    pruned + keep(0, jnp.sum(confdead, dtype=i32)),
                    dxy, dbound, dpsup, dpsupx, dcr, dside,
                    keep(d_count, d_count + n_defer))

        def cond(c):
            head, tail, overflow, waves = c[6], c[7], c[15], c[16]
            return (tail > head) & (~overflow) & (waves < wave_end)

        out = jax.lax.while_loop(cond, body, carry)
        counters = jnp.stack([
            out[11],                               # rec_count
            out[15].astype(jnp.int32),             # overflow
            out[16],                               # waves
            out[6],                                # head
            out[7],                                # tail
            out[14],                               # minsup
            out[17],                               # evaluated
            out[18],                               # pruned
            out[13],                               # n_acc
            out[25],                               # d_count
        ])
        return out, counters

    # CPU JAX ignores donation and warns about it; only donate where
    # the backend can actually alias (the HBM win the donation is for)
    donate = (tuple(range(8, 8 + N_CARRY))
              if jax.default_backend() != "cpu" else ())
    return jax.jit(run, donate_argnums=donate)


def segment_fn(caps: ResidentCaps, narrow: bool):
    """The compiled segment program at the wide or narrow wave width."""
    return _resident_fn(caps.nb_late if narrow else caps.nb, caps.km)


def count_segment(waves: int, nbw: int, km: int) -> None:
    _SEGMENTS.inc()
    if waves:
        _WAVES.inc(waves)


def count_spill(reason: str) -> None:
    _SPILLS.inc(reason=reason)


def count_deferred(n: int) -> None:
    if n > 0:
        _DEFERRED.inc(n)


def count_handoff() -> None:
    _HANDOFFS.inc()


def count_fallback() -> None:
    _FALLBACKS.inc()


def count_readback(nbytes: int) -> None:
    if nbytes > 0:
        _READBACK.inc(nbytes)
