"""JAX mirrors of ops/maxstart_np.py (constrained-SPADE max-start state).

All ops are elementwise/scan work over the position axis — VPU-friendly,
fusable, and shardable on the sequence axis exactly like the bitmap path
(positions live in the minor axis; sequences shard across devices).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NONE16 = jnp.int16(-1)


def expand_bits(words: jax.Array) -> jax.Array:
    """[..., n_words] uint32 -> [..., n_words*32] bool (LSB-first)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], words.shape[-1] * 32).astype(bool)


def root_state(words: jax.Array) -> jax.Array:
    occ = expand_bits(words)
    pos = jnp.arange(occ.shape[-1], dtype=jnp.int16)
    return jnp.where(occ, pos, NONE16)


def prev_max(m: jax.Array, maxgap: Optional[int]) -> jax.Array:
    p_axis = m.shape[-1]
    if maxgap is None or maxgap >= p_axis:
        run = jax.lax.cummax(m, axis=m.ndim - 1)
        return jnp.concatenate(
            [jnp.full(m.shape[:-1] + (1,), NONE16, m.dtype), run[..., :-1]], axis=-1)
    out = jnp.full_like(m, NONE16)
    for d in range(1, maxgap + 1):
        shifted = jnp.concatenate(
            [jnp.full(m.shape[:-1] + (d,), NONE16, m.dtype), m[..., :-d]], axis=-1)
        out = jnp.maximum(out, shifted)
    return out


def s_extend(m: jax.Array, item_words: jax.Array, maxgap: Optional[int]) -> jax.Array:
    occ = expand_bits(item_words)
    pm = prev_max(m, maxgap)
    return jnp.where(occ & (pm >= 0), pm, NONE16)


def i_extend(m: jax.Array, item_words: jax.Array) -> jax.Array:
    occ = expand_bits(item_words)
    return jnp.where(occ & (m >= 0), m, NONE16)


def support(m: jax.Array, maxwindow: Optional[int]) -> jax.Array:
    ok = m >= 0
    if maxwindow is not None:
        pos = jnp.arange(m.shape[-1], dtype=m.dtype)
        ok = ok & ((pos - m) <= maxwindow)
    return jnp.sum(jnp.any(ok, axis=-1), axis=-1, dtype=jnp.int32)
