"""SPAM wave kernels — fixed-shape s/i-extension support passes.

The SPAM formulation (Ayres et al. 2002; PAPER.md §0 names it as the
reference's algorithmic family) evaluates a frontier node against the
WHOLE item axis instead of a per-node ragged candidate list: one wave of
``Bn`` nodes costs exactly one device pass of shape
``[2*Bn, n_items_pad]`` regardless of how ragged the live candidate
sets are.  That trades wasted lanes on sparse data for zero host-side
ragged packing and a single fixed compile per geometry — the dense-data
side of the planner's crossover (service/planner.py).

The pass itself is the accelerator-friendly transformation the
"Accelerator-Oriented Algorithm Transformation" thread (PAPERS.md)
argues for: gather + s-extension shift-mask (``sext_transform``) once
per node, AND against every item bitmap, then support counting as a
popcount reduction over packed per-sequence alive bits
(``bitops_jax.pack_seq_bits``/``popcount``) — no gathers keyed by
candidate identity anywhere in the hot loop.

Layout contracts are the classic engine's verbatim (the ragged packer's
padding conventions): the store crosses jit boundaries FLAT
``[rows, S*W]`` word-minor, the ``pt`` tensor interleaves plain and
transformed parent rows (row ``2b`` = node b, row ``2b+1`` = its
s-extension transform), and padded sequences are all-zero bitmaps that
can never count.  Item rows ``n_items..ni_pad-1`` are all-zero pad rows
owned by the item region (never pool slots), so a pad lane's support is
exactly 0 rather than garbage.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, shard_map

# items per inner tile of the wave pass: bounds the broadcast AND's
# live intermediate to [2*Bn, ITEM_TILE, S, W] while keeping the lane
# axis wide enough to fill the VPU (the geometry routine sizes the node
# batch against this).
ITEM_TILE = 64


def pad_items(n_items: int, tile: int = ITEM_TILE) -> int:
    """Item-axis pad: the wave pass is a static grid of ``tile``-wide
    item tiles, so the item row count rounds up to a tile multiple."""
    return max(tile, -(-max(n_items, 1) // tile) * tile)


@functools.lru_cache(maxsize=64)
def wave_supports_fn(mesh: Optional[Mesh], n_words: int, ni_pad: int,
                     tile: int = ITEM_TILE):
    """Cached jitted wave-support pass for one (mesh, geometry).

    ``fn(pt, store) -> sup[2*Bn, ni_pad] int32``: for every interleaved
    parent row and every item row, the support of ``pt_row AND item``.
    Callers read s-extension supports at ``sup[2b+1, i]`` (transformed
    parent) and i-extension supports at ``sup[2b, i]`` (plain parent).

    Cached per wrapped-function object for the same reason as
    ``spade_tpu._spade_fns``: a per-engine closure would recompile the
    whole pass on every /train construction.
    """
    W = n_words
    n_tiles = ni_pad // tile

    def body(pt, store):
        p3 = pt.reshape(pt.shape[0], -1, W)               # [P, S, W]
        items = store[:ni_pad].reshape(n_tiles, tile, -1, W)

        def tile_sup(tile_items):                         # [tile, S, W]
            joined = p3[:, None] & tile_items[None]       # [P, tile, S, W]
            # SPAM support counting: per-sequence alive bit, packed
            # LSB-first over the sequence axis, popcount-reduced — the
            # zero tail pad in pack_seq_bits is the tail-word fix when
            # the (per-shard) sequence count is not a word multiple
            return B.support_popcount(joined)             # [P, tile]

        sup = jax.lax.map(tile_sup, items)                # [n_tiles, P, tile]
        sup = jnp.moveaxis(sup, 0, 1).reshape(p3.shape[0], ni_pad)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
        return sup

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(st, st),
                             out_specs=P()))
