"""SPAM wave kernels — fixed-shape s/i-extension support passes.

The SPAM formulation (Ayres et al. 2002; PAPER.md §0 names it as the
reference's algorithmic family) evaluates a frontier node against the
WHOLE item axis instead of a per-node ragged candidate list: one wave of
``Bn`` nodes costs exactly one device pass of shape
``[2*Bn, n_items_pad]`` regardless of how ragged the live candidate
sets are.  That trades wasted lanes on sparse data for zero host-side
ragged packing and a single fixed compile per geometry — the dense-data
side of the planner's crossover (service/planner.py).

The pass itself is the accelerator-friendly transformation the
"Accelerator-Oriented Algorithm Transformation" thread (PAPERS.md)
argues for: gather + s-extension shift-mask (``sext_transform``) once
per node, AND against every item bitmap, then support counting as a
popcount reduction over packed per-sequence alive bits
(``bitops_jax.pack_seq_bits``/``popcount``) — no gathers keyed by
candidate identity anywhere in the hot loop.

Layout contracts are the classic engine's verbatim (the ragged packer's
padding conventions): the store crosses jit boundaries FLAT
``[rows, S*W]`` word-minor, the ``pt`` tensor interleaves plain and
transformed parent rows (row ``2b`` = node b, row ``2b+1`` = its
s-extension transform), and padded sequences are all-zero bitmaps that
can never count.  Item rows ``n_items..ni_pad-1`` are all-zero pad rows
owned by the item region (never pool slots), so a pad lane's support is
exactly 0 rather than garbage.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, shard_map

# items per inner tile of the wave pass: bounds the broadcast AND's
# live intermediate to [2*Bn, ITEM_TILE, S, W] while keeping the lane
# axis wide enough to fill the VPU (the geometry routine sizes the node
# batch against this).
ITEM_TILE = 64


def pad_items(n_items: int, tile: int = ITEM_TILE) -> int:
    """Item-axis pad: the wave pass is a static grid of ``tile``-wide
    item tiles, so the item row count rounds up to a tile multiple."""
    return max(tile, -(-max(n_items, 1) // tile) * tile)


@functools.lru_cache(maxsize=64)
def wave_supports_fn(mesh: Optional[Mesh], n_words: int, ni_pad: int,
                     tile: int = ITEM_TILE):
    """Cached jitted wave-support pass for one (mesh, geometry).

    ``fn(pt, store) -> sup[2*Bn, ni_pad] int32``: for every interleaved
    parent row and every item row, the support of ``pt_row AND item``.
    Callers read s-extension supports at ``sup[2b+1, i]`` (transformed
    parent) and i-extension supports at ``sup[2b, i]`` (plain parent).

    Cached per wrapped-function object for the same reason as
    ``spade_tpu._spade_fns``: a per-engine closure would recompile the
    whole pass on every /train construction.
    """
    W = n_words
    n_tiles = ni_pad // tile

    def body(pt, store):
        p3 = pt.reshape(pt.shape[0], -1, W)               # [P, S, W]
        items = store[:ni_pad].reshape(n_tiles, tile, -1, W)

        def tile_sup(tile_items):                         # [tile, S, W]
            joined = p3[:, None] & tile_items[None]       # [P, tile, S, W]
            # SPAM support counting: per-sequence alive bit, packed
            # LSB-first over the sequence axis, popcount-reduced — the
            # zero tail pad in pack_seq_bits is the tail-word fix when
            # the (per-shard) sequence count is not a word multiple
            return B.support_popcount(joined)             # [P, tile]

        sup = jax.lax.map(tile_sup, items)                # [n_tiles, P, tile]
        sup = jnp.moveaxis(sup, 0, 1).reshape(p3.shape[0], ni_pad)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
        return sup

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(st, st),
                             out_specs=P()))


@functools.lru_cache(maxsize=64)
def wave_extend_prune_fn(mesh: Optional[Mesh], n_words: int, nd_pad: int,
                         tile: int = ITEM_TILE, use_pallas: bool = False,
                         s_block: int = 0, interpret: bool = False):
    """Fused extension-count-PRUNE wave (ISSUE 16): the wave-support
    pass with the threshold compare pushed on device and, on the Pallas
    path, into the kernel epilogue itself (ops/pallas_extend.py).

    ``fn(pt, items, thr, use_diff) -> (sup, mask)``:

    - ``pt`` [2*Bn, S*W] interleaved plain/transformed parent rows
      (flat, the store layout contract);
    - ``items`` [>= nd_pad, S*W] flat item rows — the engine's whole
      store on the pure-bitmap path, the gathered DENSE block on the
      hybrid path (the wave axis is ``nd_pad``, the dense-item pad, not
      the full item pad: sparse items never buy wave lanes);
    - ``thr`` int32 scalar (traced — one compile serves the rising
      threshold), ``use_diff`` [2*Bn] bool per-row dEclat-formulation
      flags (depth-selected by the engine);
    - ``sup`` [2*Bn, nd_pad] int32 holds the exact count where it is
      >= thr and EXACTLY 0 otherwise (thr >= 1 always, so the host's
      ``sup >= thr`` reads are byte-identical to the unfused pass);
      ``mask`` [2*Bn, nd_pad/32] uint32 packed survivor bits.

    The diffset spelling ``support(parent_row) - |diffset|`` is an exact
    identity per row (child alive-set is a subset of the parent row's),
    and it holds PER SHARD too — each shard's partial counts obey the
    same subset relation — so psum-then-threshold commutes with the
    formulation choice.  Under a mesh the threshold+pack runs post-psum
    inside the same shard_map body (on device, one launch); only the
    single-device Pallas path prunes inside the kernel epilogue.
    """
    W = n_words
    n_tiles = nd_pad // tile

    def body(pt, items, thr, use_diff):
        p3 = pt.reshape(pt.shape[0], -1, W)               # [P, S, W]
        parent_alive = B.contains_bits(p3)                # [P, S]
        parent_pop = B.alive_popcount(parent_alive)       # [P]
        if use_pallas:
            from spark_fsm_tpu.ops import pallas_support as PS

            # kernel layout + tile padding: parent rows up to the
            # 16-row tile, item rows up to the 128-lane item tile
            # (nd_pad is a 64-multiple; pad rows are all-zero -> sup 0)
            p = p3.shape[0]
            p_pad = -(-p // PS.P_TILE) * PS.P_TILE
            ptk = jnp.transpose(p3, (0, 2, 1))            # [P, W, S]
            if p_pad != p:
                ptk = jnp.pad(ptk, ((0, p_pad - p), (0, 0), (0, 0)))
            itk = jnp.transpose(
                items[:nd_pad].reshape(nd_pad, -1, W), (0, 2, 1))
            ni128 = -(-nd_pad // 128) * 128
            if ni128 != nd_pad:
                itk = jnp.pad(itk, ((0, ni128 - nd_pad), (0, 0), (0, 0)))
            if mesh is None:
                from spark_fsm_tpu.ops import pallas_extend as PE

                sup, mask = PE.extend_count_prune(
                    ptk, itk, thr, nd_pad, s_block=s_block,
                    interpret=interpret)
                # direct count == diffset count (exact identity):
                # use_diff changes the accounting, never the bytes
                return sup[:p, :nd_pad], mask[:p, :nd_pad // 32]
            sup = PS.pair_supports(ptk, itk, nd_pad, s_block=s_block,
                                   interpret=interpret)[:p, :nd_pad]
        else:
            items4 = items[:nd_pad].reshape(n_tiles, tile, -1, W)

            def tile_sup(tile_items):                     # [tile, S, W]
                joined = p3[:, None] & tile_items[None]   # [P, tile, S, W]
                child_alive = B.contains_bits(joined)     # [P, tile, S]
                direct = B.alive_popcount(child_alive)
                diff = B.support_from_diffset(
                    parent_pop[:, None],
                    B.diffset_count(parent_alive[:, None], child_alive))
                return jnp.where(use_diff[:, None], diff, direct)

            sup = jax.lax.map(tile_sup, items4)           # [n_tiles, P, tile]
            sup = jnp.moveaxis(sup, 0, 1).reshape(p3.shape[0], nd_pad)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
        alive = sup >= thr
        return jnp.where(alive, sup, 0), B.pack_seq_bits(alive)

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS)
    # check_vma=False for the same reason as spade_tpu's pallas wrap:
    # pallas_call carries no varying-mesh-axes rule, so the replication
    # checker cannot see through it on the kernel path
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(st, st, P(), P()),
                             out_specs=(P(), P()), check_vma=False))


@functools.lru_cache(maxsize=8)
def gather_rows_fn(mesh: Optional[Mesh]):
    """Cached jitted dense-block gather for the hybrid store: pull the
    planner's DENSE item rows out of the full store into a compact
    ``[nd_pad, S*W]`` block the wave pass iterates over.  ``rows`` is a
    host-built int32 index vector with -1 marking pad rows (gathered as
    all-zero, so a pad wave lane's support is exactly 0).  Item rows are
    immutable after the scatter build — materialize/recompute only ever
    write pool slots — so one gather at construction serves the whole
    mine."""

    def body(store, rows):
        safe = jnp.maximum(rows, 0)
        return jnp.where((rows >= 0)[:, None], store[safe], jnp.uint32(0))

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(st, P()),
                             out_specs=st))


@functools.lru_cache(maxsize=64)
def pair_prune_fn(mesh: Optional[Mesh], n_words: int):
    """Fused gather-join-count-prune for the SPARSE (id-list) half of
    the hybrid store: candidates whose item the planner routed to the
    id-list representation never buy a full wave lane — they are
    evaluated as an explicit (parent row, item row) pair list at pow2
    widths (the engine chunks and pads; compiled once per width).

    ``fn(pt, store, pref, item, thr, use_diff) -> sup [C] int32``:
    ``pref`` indexes the interleaved pt rows (2b plain / 2b+1
    transformed), ``item`` the store's item rows with -1 marking pad
    lanes (masked to 0 on output), ``use_diff`` selects the dEclat
    formulation per candidate.  Output follows the fused-prune
    contract: exact count where >= thr, exactly 0 otherwise."""
    W = n_words

    def body(pt, store, pref, item, thr, use_diff):
        p3 = pt.reshape(pt.shape[0], -1, W)               # [P, S, W]
        prows = p3[pref]                                  # [C, S, W]
        safe = jnp.maximum(item, 0)
        irows = store[safe].reshape(item.shape[0], -1, W)  # [C, S, W]
        child_alive = B.contains_bits(prows & irows)      # [C, S]
        parent_alive = B.contains_bits(prows)
        direct = B.alive_popcount(child_alive)
        diff = B.support_from_diffset(
            B.alive_popcount(parent_alive),
            B.diffset_count(parent_alive, child_alive))
        sup = jnp.where(use_diff, diff, direct)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
        return jnp.where((item >= 0) & (sup >= thr), sup, 0)

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS)
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(st, st, P(), P(), P(), P()),
                             out_specs=P()))
