"""Bitmap join/support kernels: NumPy reference, jax.numpy, and Pallas TPU."""
