"""Equivalence-class candidate partitioning over the outer mesh axis.

The mesh path before this layer was PURE data parallelism: the sequence
axis shards over devices, every shard evaluates the SAME replicated
candidate set, a ``psum`` crosses the full mesh (and hence DCN on a
pod) at every wave, and the host-side DFS enumeration runs duplicated
SPMD on every process.  That is one end of the trade-off mapped by
RDD-Eclat (arxiv 1912.06415) and the parallel-SPM survey (arxiv
1805.10515): *shard the data, replicate the candidates*.  This module
adds the other axis — *partition the candidates, replicate (or
inner-shard) the data* — and composes the two into a 2-D ``hosts x
seq`` mesh:

- the mining frontier splits by EQUIVALENCE CLASS over the outer
  ``part`` axis.  A candidate's class is decided by its km-prefix — for
  TSR the root item ``min(X)`` (invariant under both left and right
  expansion: X grows only by larger indices, Y never touches it), for
  SPADE/cSPADE the pattern's first item (the DFS root; itemset
  extensions only add larger items, so every pattern has exactly one
  root).  Classes hash from GLOBAL item ids (:func:`class_of`), so
  ownership is stable across iterative-deepening rounds and identical
  on every process with zero coordination;
- classes balance across partitions by the committed cost model's
  per-class lane estimates (:func:`plan_partitions`): a root's subtree
  dispatches candidate lanes roughly proportional to its item support
  (support bounds how deep its sibling chains survive the rising
  threshold), so per-class cost = sum of owned item supports, assigned
  LPT (longest-processing-time first).  The achieved balance is
  exported as ``fsm_partition_imbalance_ratio``;
- each partition keeps today's INNER seq-axis shard + ICI ``psum``
  (:func:`submeshes` splits a flat device mesh into per-partition rows),
  so cross-partition traffic drops from a per-wave full-mesh ``psum``
  to a small per-round exchange (:func:`exchange_objects`): TSR
  partitions all-reduce a conservative top-k floor and the final exact
  merge; SPADE partitions exchange only the final pattern slices.

Partition-aware candidate generation means each process enumerates
ONLY its owned classes — the replicated-DFS host work finally scales
with hosts instead of being duplicated on every one of them.

Everything here is host arithmetic except :func:`exchange_objects`,
which uses a device collective only in multi-controller runs (one tiny
all-gather per exchange round — the DCN bill is per ROUND, not per
wave; counted in ``fsm_partition_cross_bytes_total``).
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from spark_fsm_tpu.utils import obs  # host-only (no jax import here)

PART_AXIS = "part"

# --------------------------------------------------------------- metrics

_PLANS = obs.REGISTRY.counter(
    "fsm_partition_plans_total",
    "equivalence-class partition plans built (parallel/partition.py)")
_EXCHANGES = obs.REGISTRY.counter(
    "fsm_partition_exchange_rounds_total",
    "cross-partition exchange rounds (threshold floor + result merge); "
    "the partitioned path's ONLY cross-partition collective — scales "
    "with rounds, never with launches")
_CROSS_BYTES = obs.REGISTRY.counter(
    "fsm_partition_cross_bytes_total",
    "bytes exchanged across partitions (payload size; host-local in "
    "single-controller runs, a DCN all-gather in multi-controller ones)")
_IMBALANCE = obs.REGISTRY.gauge(
    "fsm_partition_imbalance_ratio",
    "max/mean per-partition cost of the latest plan (1.0 = perfect)")
# known algo vocabulary zero-seeded (the obs_smoke no-orphan contract)
_MINES = obs.REGISTRY.counter(
    "fsm_partition_mines_total",
    "partitioned mines run, by algorithm")
for _algo in ("tsr", "spade", "cspade"):
    _MINES.seed(algo=_algo)
_IMBALANCE.set(0.0)


# ------------------------------------------------------------ class hash

# splitmix64 finalizer constants — a fixed, seedless avalanche over the
# GLOBAL item id so every process computes the identical class map with
# zero coordination (and the map survives restarts / deepening rounds)
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)


def class_of(item_ids, n_classes: int) -> np.ndarray:
    """Equivalence-class index (km-prefix hash) for global item ids.

    Vectorized splitmix64 finalizer: classes must be uncorrelated with
    id magnitude (real alphabets cluster hot items at low ids) yet
    identical everywhere — a seeded or process-local hash would break
    the zero-coordination ownership contract."""
    x = np.asarray(item_ids, dtype=np.uint64)
    x = (x ^ (x >> np.uint64(30))) * _C1
    x = (x ^ (x >> np.uint64(27))) * _C2
    x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(int(n_classes))).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """A committed class->partition assignment.

    ``owner[c]`` is the partition owning class ``c``; ``part_costs`` is
    the modeled lane cost each partition carries.  The plan is a pure
    function of (item ids, item supports, n_parts, n_classes), so every
    process building it from the same (replicated) vertical DB owns the
    same classes — candidate generation needs no ownership messages.
    """

    n_parts: int
    n_classes: int
    owner: np.ndarray  # [n_classes] int32
    part_costs: np.ndarray  # [n_parts] float64
    # per-class modeled costs, kept so a degraded re-plan
    # (:func:`replan_surviving`) can LPT-rebalance a dead row's classes
    # without the vertical DB in hand; None on plans built before the
    # topology-survival plane (re-plans then assume uniform class cost)
    class_costs: Optional[np.ndarray] = None

    @property
    def imbalance_ratio(self) -> float:
        mean = float(self.part_costs.mean()) if self.n_parts else 0.0
        if mean <= 0:
            return 1.0
        return float(self.part_costs.max()) / mean

    def owner_of(self, item_ids) -> np.ndarray:
        """Partition index owning each item's class (vectorized)."""
        return self.owner[class_of(item_ids, self.n_classes)]

    def owned_slice(self, roots: Sequence[int], item_ids,
                    part: int) -> List[int]:
        """Filter LOCAL root indices to those whose class ``part``
        owns (``item_ids[r]`` maps a local index to its global id) —
        the ONE spelling of the seed filter every engine's
        partition-aware root seeding goes through, so ownership
        semantics cannot drift between engines."""
        roots = list(roots)
        if not roots:
            return roots
        own = self.owner_of(
            np.asarray(item_ids)[np.asarray(roots, np.int64)]
        ) == int(part)
        return [r for r, o in zip(roots, own) if o]

    def fingerprint(self) -> dict:
        """What a partitioned checkpoint binds to: a changed layout must
        restart fresh, never resume another layout's class slices."""
        return {"parts": int(self.n_parts), "classes": int(self.n_classes),
                "owner_sum": int(self.owner.astype(np.int64).sum())}


def plan_partitions(item_ids, item_supports, n_parts: int,
                    n_classes: int = 64, *,
                    record: bool = True) -> PartitionPlan:
    """Balance equivalence classes over ``n_parts`` partitions.

    Per-class cost is the committed cost model's lane estimate: a root
    item's subtree dispatches candidate lanes roughly proportional to
    its support (items are support-sorted and sibling-chain bounds are
    ``min(psup, sup_j)``, so higher-support roots keep more of their
    chains above the rising threshold) — the same units
    (candidate-lanes) the ragged packer's cost model prices.  Classes
    are assigned LPT (largest class first to the least-loaded
    partition), the classic 4/3-approximation, which is exact enough
    here because the class count (default 64) is much larger than the
    partition count.
    """
    n_parts = int(n_parts)
    n_classes = int(n_classes)
    if n_parts < 1:
        raise ValueError(f"n_parts must be >= 1, got {n_parts}")
    if n_classes < n_parts:
        raise ValueError(
            f"n_classes ({n_classes}) must be >= n_parts ({n_parts})")
    cls = class_of(item_ids, n_classes)
    costs = np.bincount(cls, weights=np.asarray(item_supports,
                                                np.float64),
                        minlength=n_classes)
    owner = np.zeros(n_classes, np.int32)
    load = np.zeros(n_parts, np.float64)
    # LPT: stable sort keeps the plan deterministic across numpy versions
    for c in np.argsort(-costs, kind="stable"):
        p = int(np.argmin(load))
        owner[int(c)] = p
        load[p] += costs[int(c)]
    plan = PartitionPlan(n_parts, n_classes, owner, load, costs)
    if record:
        _PLANS.inc()
        _IMBALANCE.set(plan.imbalance_ratio)
    return plan


def replan_surviving(plan: PartitionPlan,
                     dead_rows: Sequence[int]) -> PartitionPlan:
    """Re-balance a dead row's equivalence classes onto the survivors.

    Class hashes (:func:`class_of`) are TOPOLOGY-INDEPENDENT — a class
    is a pure function of global item ids, not of which silicon owns it
    — so ownership recomputes with zero coordination: surviving rows
    KEEP their classes (their in-flight frontiers and checkpoints stay
    valid), and only the dead rows' classes re-assign, LPT (largest
    class first to the least-loaded survivor) over the per-class costs
    the original plan recorded.  Dead partitions end with zero cost and
    an empty class set; the layout geometry (``n_parts``/``n_classes``)
    is unchanged, but the owner map is not — so
    :meth:`PartitionPlan.fingerprint` CHANGES, and a composite
    checkpoint taken under the old layout restarts fresh rather than
    resuming per-part slices that no longer mean the same classes.
    (In-flight adoption therefore keeps the ORIGINAL plan and re-homes
    whole slices via :func:`adopters_for` instead.)  Byte parity of the
    merged result follows either way (docs/DESIGN.md): every class is
    still mined exactly once, under the same minsup / conservative
    floor, and the merge sorts.
    """
    dead = {int(r) for r in dead_rows}
    survivors = [p for p in range(plan.n_parts) if p not in dead]
    if not survivors:
        raise ValueError(
            f"no surviving partitions (dead={sorted(dead)} of "
            f"{plan.n_parts}): the mesh is gone, not degraded")
    if not dead:
        return plan
    costs = (plan.class_costs if plan.class_costs is not None
             else np.ones(plan.n_classes, np.float64))
    owner = plan.owner.copy()
    load = np.zeros(plan.n_parts, np.float64)
    for c in range(plan.n_classes):
        if int(owner[c]) not in dead:
            load[int(owner[c])] += costs[c]
    orphan_classes = [c for c in range(plan.n_classes)
                     if int(owner[c]) in dead]
    # LPT over the orphaned classes only — stable sort, so every
    # process (and every retry) derives the identical adoption map
    orphan_classes.sort(key=lambda c: (-costs[c], c))
    for c in orphan_classes:
        p = survivors[int(np.argmin(load[survivors]))]
        owner[c] = p
        load[p] += costs[c]
    return PartitionPlan(plan.n_parts, plan.n_classes, owner, load,
                         plan.class_costs)


def adopters_for(plan: PartitionPlan,
                 dead_rows: Sequence[int]) -> dict:
    """Deterministic ``dead part -> surviving adopter`` map for
    in-flight slice adoption: each dead part's WHOLE remaining slice
    re-homes onto the least-loaded survivor (largest dead part first —
    the same LPT discipline as :func:`replan_surviving`, applied at
    part granularity because a mid-mine slice must keep its original
    class restriction for checkpoint compatibility; only the silicon
    underneath it changes)."""
    dead = sorted({int(r) for r in dead_rows},
                  key=lambda r: (-float(plan.part_costs[r]), r))
    survivors = [p for p in range(plan.n_parts)
                 if p not in set(dead)]
    if not survivors:
        raise ValueError(
            f"no surviving partitions (dead={sorted(dead)} of "
            f"{plan.n_parts}): the mesh is gone, not degraded")
    load = plan.part_costs.astype(np.float64).copy()
    out = {}
    for r in dead:
        p = survivors[int(np.argmin(load[survivors]))]
        out[r] = p
        load[p] += float(plan.part_costs[r])
    return out


# ------------------------------------------------------------- 2-D mesh


def submeshes(mesh, n_parts: int) -> List[Optional[object]]:
    """Split a flat device mesh into per-partition INNER seq meshes —
    the rows of the ``hosts x seq`` 2-D arrangement.

    Single controller: the first ``n_parts * inner`` devices reshape to
    ``(n_parts, inner)`` and each row becomes a 1-D seq mesh — a
    one-device row still gets a one-device MESH, not ``None``: the mesh
    is what pins each partition's dispatches to its OWN device (a None
    row would land every partition on the default device and idle the
    rest — trading the resident-frontier/fusion eligibility of the bare
    single-device path for actual silicon is the point of partitioning
    a real multi-device mesh).  ``mesh=None`` maps every partition onto
    the one local device (there is no silicon to spread — partitioning
    there is a routing/correctness regime, and the bare single-device
    path keeps its resident/fusion eligibility).

    Multi controller: each partition's row must be PROCESS-LOCAL (the
    whole point — no per-wave collective may cross partitions), so
    ``n_parts`` must equal the process count and partition ``p`` gets
    process ``p``'s local devices.  A one-LOCAL-device process keeps
    ``None`` (its default device IS its row).  Equal-geometry rows
    produce equal shape keys, so the compiled ladder stays enumerable.
    """
    n_parts = int(n_parts)
    if n_parts <= 1:
        return [mesh]
    if mesh is None:
        return [None] * n_parts
    from jax.sharding import Mesh

    from spark_fsm_tpu.parallel.mesh import SEQ_AXIS

    devs = list(mesh.devices.flat)
    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    if len(by_proc) > 1:
        if n_parts != len(by_proc):
            raise ValueError(
                f"multi-controller partitioning needs one partition per "
                f"process (got parts={n_parts}, processes={len(by_proc)}): "
                f"a partition row spanning processes would reintroduce "
                f"the per-wave DCN collective this layer removes")
        rows = [by_proc[pi] for pi in sorted(by_proc)]
        # a process with one local device runs its slice on its default
        # device already — keep the engines' bare single-device path
        return [None if len(row) == 1
                else Mesh(np.asarray(row), (SEQ_AXIS,)) for row in rows]
    if len(devs) % n_parts:
        raise ValueError(
            f"mesh of {len(devs)} devices does not split into "
            f"{n_parts} equal partition rows")
    inner = len(devs) // n_parts
    rows = [devs[p * inner:(p + 1) * inner] for p in range(n_parts)]
    return [Mesh(np.asarray(row), (SEQ_AXIS,)) for row in rows]


def owned_parts(plan: PartitionPlan) -> List[int]:
    """The partitions THIS process enumerates.  Single controller owns
    all of them (and runs them sequentially over its submesh rows);
    in a multi-controller run partition ``p`` belongs to process ``p``
    (the :func:`submeshes` row contract)."""
    import jax

    if jax.process_count() == 1:
        return list(range(plan.n_parts))
    return [jax.process_index()]


# ------------------------------------------------------------- exchange


def exchange_objects(payload, *, stats: Optional[dict] = None,
                     record: bool = True) -> list:
    """One cross-partition exchange round: every process contributes
    ``payload`` (any JSON-able object) and receives the list of all
    processes' payloads, in process order.

    Single controller: the calling orchestrator already holds every
    partition's data, so the exchange is a host-local no-op returning
    ``[payload]`` — but it still counts an exchange round and the
    payload bytes, so the scaling-curve counters mean the same thing at
    every scale (what WOULD cross the partition axis).

    Multi controller: a padded ``uint8`` all-gather over the global
    device set (jax.experimental.multihost_utils), i.e. ONE tiny DCN
    collective per round — the whole point of the partitioned regime is
    that this, not the per-wave support ``psum``, is the only traffic
    that crosses hosts.

    ``stats``: an engine stats dict to mirror the round/byte counters
    into (``partition_exchanges`` / ``partition_cross_bytes``) next to
    the process-global registry families; ``record=False`` (warm runs)
    skips the registry families but still fills ``stats``.
    """
    import json

    import jax

    blob = json.dumps(payload).encode("utf-8")
    if jax.process_count() == 1:
        nbytes = len(blob)
        merged = [payload]
    else:
        from jax.experimental import multihost_utils

        lens = np.asarray(
            multihost_utils.process_allgather(np.int64(len(blob))),
            np.int64).reshape(-1)
        width = int(lens.max())
        buf = np.zeros(width, np.uint8)
        buf[:len(blob)] = np.frombuffer(blob, np.uint8)
        rows = np.asarray(multihost_utils.process_allgather(buf))
        rows = rows.reshape(len(lens), width)
        nbytes = int(lens.sum())
        merged = [
            json.loads(rows[i, :int(lens[i])].tobytes().decode("utf-8"))
            for i in range(len(lens))]
    if record:
        _EXCHANGES.inc()
        _CROSS_BYTES.inc(nbytes)
    if stats is not None:
        stats["partition_exchanges"] = (
            stats.get("partition_exchanges", 0) + 1)
        stats["partition_cross_bytes"] = (
            stats.get("partition_cross_bytes", 0) + nbytes)
    return merged


class ThresholdBoard:
    """Conservative global top-k floor, monotonically tightening.

    Partitions publish the supports of their accepted rules; the floor
    is the k-th largest support seen so far across ALL published
    results — a LOWER bound on the global top-k threshold (the global
    threshold is the k-th largest over a superset), so a partition that
    starts its search with ``minsup = floor`` prunes only candidates
    that can never enter the global top-k.  ``merge`` only ever raises
    the floor (docs/DESIGN.md states the exactness argument)."""

    def __init__(self, k: int, floor: int = 1):
        self.k = int(k)
        self._floor = max(1, int(floor))
        self._sups: List[int] = []  # top-k supports seen, ascending

    def floor(self) -> int:
        return self._floor

    def merge(self, supports: Sequence[int]) -> int:
        for s in supports:
            s = int(s)
            if len(self._sups) < self.k:
                bisect.insort(self._sups, s)
            elif s > self._sups[0]:
                self._sups.pop(0)
                bisect.insort(self._sups, s)
        if len(self._sups) >= self.k and self._sups[0] > self._floor:
            self._floor = self._sups[0]
        return self._floor


def count_mine(algo: str) -> None:
    _MINES.inc(algo=algo)


def fold_numeric_stats(dst: dict, src: dict) -> None:
    """Additively fold one engine's numeric counters into an
    orchestrator stats dict — the ONE spelling of the partitioned
    stats merge (strings/bools/containers skipped), so the bench and
    smoke exports cannot drift between the TSR and SPADE routes."""
    for key, v in src.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        dst[key] = dst.get(key, 0) + v


def encode_patterns(results) -> list:
    """(pattern, support) results -> JSON rows for the exchange; the
    inverse of :func:`decode_patterns`."""
    return [[[list(its) for its in pat], int(sup)]
            for pat, sup in results]


def decode_patterns(rows) -> list:
    return [(tuple(tuple(int(i) for i in its) for its in pat), int(sup))
            for pat, sup in rows]


def composite_state(fingerprint: dict, done: dict, active_part,
                    active_state, **extra) -> dict:
    """The ONE spelling of the partitioned composite checkpoint:
    merged rows at top level in rewrite mode (StoreCheckpoint's
    ``results_done=0`` contract) plus each partition's frontier
    UNCHANGED in the engines' own ``frontier_state`` format.  Both
    orchestrators (TSR rounds, SPADE/cSPADE slices) build and decode
    through here so the crash-recovery schema has a single owner."""
    return {
        "version": 1,
        "fingerprint": fingerprint,
        "stack": [],
        "results": [r for p in sorted(done) for r in done[p]],
        "results_done": 0,
        "partition": {
            "done": {str(p): done[p] for p in sorted(done)},
            "active_part": active_part,
            "active_state": active_state,
        },
        **extra,
    }


def decode_composite(resume: Optional[dict], fingerprint: dict):
    """(done, active_resume) from a composite snapshot; empty when the
    snapshot is missing or bound to another layout."""
    done: dict = {}
    active_resume: dict = {}
    if resume is not None and resume.get("fingerprint") == fingerprint:
        pr = resume.get("partition", {})
        for p_s, rows_p in pr.get("done", {}).items():
            done[int(p_s)] = [list(r) for r in rows_p]
        ap = pr.get("active_part")
        if ap is not None and pr.get("active_state") is not None:
            active_resume[int(ap)] = pr["active_state"]
    return done, active_resume


def mine_partitioned_slices(*, plan: PartitionPlan, meshes: list,
                            fingerprint: dict, mine_part,
                            resume: Optional[dict] = None,
                            checkpoint_cb=None,
                            stats: Optional[dict] = None) -> list:
    """Run fully-independent class slices (the SPADE/cSPADE regime:
    fixed minsup, no dynamic threshold — partitions share only the F1
    seed already present in the replicated vertical DB) and exchange
    the result slices once at the end.

    ``mine_part(p, inner_mesh, resume_state, part_cb)`` mines partition
    ``p``'s slice and returns its results as JSON-able rows; it
    receives the part's resumed ``frontier_state`` (or None) and a
    callback to forward the engine's own frontier snapshots through.
    Checkpoints are composite: merged rows at top level (rewrite mode)
    plus the active part's frontier UNCHANGED in the engine's own
    ``frontier_state`` format, fingerprint-bound to the partition
    layout.  Returns the union of every partition's rows (across
    processes too — one exchange round).

    Topology survival (service/meshguard.py, when installed): a part
    whose dispatch dies device-shaped marks its mesh row suspect/dead;
    a dead row's slice RE-HOMES onto the :func:`adopters_for` survivor
    — same part index, same class restriction, same resumed frontier
    (the last snapshot the part forwarded), different silicon — so the
    merged union stays byte-identical to the healthy run."""
    done, active_resume = decode_composite(resume, fingerprint)
    guard = None
    MG = None
    try:  # lazy, like the jax import in exchange_objects: the parallel
        from spark_fsm_tpu.service import meshguard as MG  # layer must
        guard = MG.get()  # not hard-depend on the service layer
    except Exception:
        guard = None

    def composite(active_part, active_state):
        return composite_state(fingerprint, done, active_part,
                               active_state)

    for p in owned_parts(plan):
        if p in done:
            continue
        last = {"fs": active_resume.get(p)}
        part_cb = None
        if checkpoint_cb is not None or guard is not None:
            def part_cb(fs, p=p, last=last):
                last["fs"] = fs  # adoption resume point, even with no
                if checkpoint_cb is not None:  # durable checkpoint sink
                    checkpoint_cb(composite(p, fs))
        row, attempts = p, 0
        while True:
            try:
                done[p] = list(mine_part(p, meshes[row], last["fs"],
                                         part_cb))
                if guard is not None:
                    guard.note_row_ok(row)
                break
            except Exception as exc:
                if guard is None:
                    raise
                state = guard.note_row_fault(row, exc)
                attempts += 1
                if state is None or attempts >= guard.max_retries:
                    raise  # not device-shaped, or the mesh is melting
                if state == MG.DEAD:
                    adopter = adopters_for(
                        plan, guard.dead_rows()).get(row)
                    if adopter is None or adopter == row:
                        raise
                    MG.note_replan(guard.dead_rows())
                    row = adopter
                # suspect: one more try on the same row
        if checkpoint_cb is not None:
            checkpoint_cb(composite(None, None))
    # contribute ONLY owned parts to the exchange: a resumed composite
    # from a shared checkpoint can carry other processes' completed
    # slices, and re-contributing them would duplicate rows in the
    # merged union (every live process contributes its own)
    own = set(owned_parts(plan))
    gathered = exchange_objects(
        {"rows": [r for p in sorted(done) if p in own
                  for r in done[p]]}, stats=stats)
    return [r for g in gathered for r in g["rows"]]
