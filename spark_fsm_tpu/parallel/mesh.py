"""Mesh construction and sharding helpers.

The framework's parallel axis is the *sequence-id* axis of the vertical
bitmap DB (SURVEY.md sec 2.2): joins are elementwise over sequences, so the
only communication is the ``psum`` of per-shard partial supports over ICI
before the global minsup prune.  This is the TPU-native replacement for the
reference's Spark-partition data parallelism + driver-side aggregation.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEQ_AXIS = "seq"

# jax.shard_map moved namespaces across jax versions (top-level on
# current jax, jax.experimental.shard_map before) and renamed its
# replication-check kwarg (check_rep -> check_vma).  ONE compat symbol —
# every engine imports it from here, so the repo runs on either.
try:
    shard_map = jax.shard_map
except AttributeError:  # pre-move jax: experimental namespace + old kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(f, **kw)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the sequence axis.  Multi-host: pass jax.devices()."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SEQ_AXIS,))

def store_spec() -> P:
    """[slot, seq, word] bitmap store: shard the sequence axis."""
    return P(None, SEQ_AXIS, None)


def store_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, store_spec())


def pad_to_multiple(n: int, k: int) -> int:
    return -(-n // k) * k
