"""Multi-host (DCN) seam: ``jax.distributed`` wiring + global-array helpers.

The reference scales past one machine with Spark driver->executor RPC and
Akka remoting over TCP (SURVEY.md sec 2.2 rows 3-4, sec 5 comms row).  The
TPU-native replacement is JAX's multi-controller model: every host runs the
SAME program, ``jax.distributed.initialize`` wires them into one runtime
over DCN, and a ``Mesh`` over ``jax.devices()`` (all hosts' chips) makes
the seq-axis ``shard_map``/``psum`` pipeline span hosts with no further
code change — the ICI collectives simply ride DCN at the host boundary.

Host-side orchestration stays SPMD: each process runs the identical DFS
control flow on identical (replicated) support readbacks, so no extra
cross-host messaging is needed — the determinism the reference gets from a
single Spark driver, the rebuild gets from replicated reductions.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Wire this process into the multi-host runtime (idempotent).

    Args fall back to JAX's standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) and cloud auto-detection when
    omitted — on a real TPU pod slice ``jax.distributed.initialize()`` with
    no arguments resolves everything from the metadata server.
    """
    global _initialized
    if _initialized:
        return
    # NOTE: no jax.process_count()/jax.devices() probing here — touching the
    # backend before jax.distributed.initialize() is itself the error.
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = (
            num_processes if num_processes is not None
            else int(os.environ["JAX_NUM_PROCESSES"]))
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = (
            process_id if process_id is not None
            else int(os.environ["JAX_PROCESS_ID"]))
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as exc:
        # Tolerate a runtime someone else already wired (e.g. a launcher
        # that called initialize before importing this package).  JAX's
        # message is "distributed.initialize should only be called once."
        msg = str(exc)
        if "only be called once" not in msg and "already initialized" not in msg:
            raise
    _initialized = True


def shutdown_distributed() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def is_multihost(mesh) -> bool:
    """True when ``mesh`` spans more than one controller process.

    Checked against the mesh's OWN devices, not just the runtime's
    process count: a partitioned mine (parallel/partition.py) runs
    engines over process-LOCAL inner submeshes inside a multi-controller
    runtime, and those must take the plain local-device paths — a local
    mesh has no cross-process collective to feed.  The process-count
    fast path keeps single-controller callers (every ``_put`` on the
    engine hot paths goes through here) at one int compare instead of a
    device scan."""
    if mesh is None or jax.process_count() == 1:
        return False
    it = iter(mesh.devices.flat)
    first = next(it).process_index
    return any(d.process_index != first for d in it)


def host_to_device(mesh, x) -> jax.Array:
    """Host array -> device input for an engine's (possibly multi-host)
    mesh fns: plain ``jnp.asarray`` single-controller, a global replicated
    array otherwise (SPMD host loops keep per-process copies identical).
    The single shared implementation behind every engine's ``_put``.
    ``jnp`` is imported at module scope — this is the single-controller
    HOT path (one call per staged candidate buffer), and a function-local
    import re-enters the import lock on every call."""
    if is_multihost(mesh):
        return replicate(mesh, x)
    return jnp.asarray(x)


def replicate(mesh: Mesh, x) -> jax.Array:
    """Host array -> fully-replicated global array over ``mesh``.

    In a single process this is a plain ``device_put`` (jit would have done
    it implicitly); across processes a committed single-device array cannot
    feed a multi-host computation, so every process contributes its (by
    SPMD construction identical) local copy as the replica.
    """
    x = np.asarray(x)
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)
