"""Device-mesh parallelism: sequence-axis sharding, psum support reduction."""
