"""spark_fsm_tpu — a TPU-native frequent-sequence-mining framework.

Rebuilds the capabilities of ``databill86/spark-fsm`` (a Scala/Spark + Akka
service wrapping the SPMF SPADE frequent-sequence miner and the TSR top-k
sequential-rule miner) as an idiomatic JAX/Pallas framework:

- the vertical sequence database is an HBM-resident ``item x seq x word``
  bitmap tensor (SPAM-style id-lists, SURVEY.md sec 2.3 step 1);
- the SPADE temporal joins (s-extension / i-extension) and support counts are
  bitwise VPU kernels (``ops/``), batched over candidates;
- the sequence axis shards over a ``jax.sharding.Mesh`` with partial supports
  ``psum``-reduced over ICI before the global minsup prune (``parallel/``);
- the service shell preserves the reference's contracts: SPMF dataset format,
  ``algorithm={SPADE,SPADE_TPU,TSR,TSR_TPU}`` plugin selection, and the
  train/status/get/track/register job lifecycle (``service/``).

The reference mount was empty during the survey (see SURVEY.md provenance
notice), so parity is defined behaviorally: byte-identical frequent-sequence
sets versus the CPU oracle in ``models/oracle.py`` on the BASELINE.md configs.
"""

__version__ = "0.1.0"

from spark_fsm_tpu.data.spmf import parse_spmf, format_spmf  # noqa: F401
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical  # noqa: F401
