"""Deterministic synthetic sequence databases.

The sandbox has no network egress, so the public benchmark datasets named in
BASELINE.md (BMS-WebView-1/2, MSNBC, Kosarak, Gazelle) cannot be downloaded.
These generators produce seeded databases matched to the documented shape of
each dataset (sequence count, alphabet size, length distribution, Zipfian
item popularity) so benchmarks and parity tests are reproducible.  Swap in
the real files via ``data.spmf.load_spmf`` when available — every consumer
takes a plain SequenceDB.
"""

from __future__ import annotations

import numpy as np

from spark_fsm_tpu.data.spmf import SequenceDB


def synthetic_db(
    seed: int,
    n_sequences: int,
    n_items: int,
    mean_itemsets: float,
    mean_itemset_size: float = 1.0,
    zipf_s: float = 1.2,
    max_itemsets: int = 96,
    correlation: float = 0.35,
) -> SequenceDB:
    """Generate a clickstream-like sequence DB.

    Item popularity is Zipfian (rank-``zipf_s``); ``correlation`` is the
    probability that the next itemset is drawn from a small per-sequence
    working set instead of globally, which creates genuine frequent patterns
    (pure i.i.d. draws would leave little to mine).
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    probs = ranks ** (-zipf_s)
    probs /= probs.sum()

    lengths = 1 + rng.poisson(max(mean_itemsets - 1.0, 0.0), size=n_sequences)
    lengths = np.minimum(lengths, max_itemsets)
    sizes_extra = rng.poisson(max(mean_itemset_size - 1.0, 0.0), size=int(lengths.sum()))

    db: SequenceDB = []
    k = 0
    for n in lengths:
        # Per-sequence working set of a few popular items → shared patterns.
        wset = rng.choice(n_items, size=min(6, n_items), replace=False, p=probs) + 1
        seq = []
        for _ in range(int(n)):
            sz = 1 + int(sizes_extra[k])
            k += 1
            itemset = set()
            for _ in range(sz):
                if rng.random() < correlation:
                    itemset.add(int(wset[rng.integers(len(wset))]))
                else:
                    itemset.add(int(rng.choice(n_items, p=probs)) + 1)
            seq.append(tuple(sorted(itemset)))
        db.append(tuple(seq))
    return db


def synthetic_db_fast(
    seed: int,
    n_sequences: int,
    n_items: int,
    mean_itemsets: float,
    mean_itemset_size: float = 1.0,
    zipf_s: float = 1.2,
    max_itemsets: int = 96,
    correlation: float = 0.35,
) -> SequenceDB:
    """Vectorized variant of :func:`synthetic_db` for full-scale databases.

    Same distribution family (Zipfian popularity, Poisson lengths,
    per-sequence working sets) but every token is drawn with one
    inverse-CDF ``searchsorted`` pass instead of a per-token
    ``rng.choice`` over the whole alphabet, which is O(n_items) per draw
    and makes the exact generator take ~35 minutes for a full
    Kosarak-shaped DB (990k sequences x 41k items) where this takes
    seconds.  NOT seed-compatible with ``synthetic_db`` (different rng
    consumption order; working sets sample with replacement), so the two
    generators produce different databases for the same seed — use this
    for scale experiments, the exact one for anything whose numbers are
    compared across runs of the other.
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    probs = ranks ** (-zipf_s)
    probs /= probs.sum()
    cdf = np.cumsum(probs)

    lengths = 1 + rng.poisson(max(mean_itemsets - 1.0, 0.0), size=n_sequences)
    lengths = np.minimum(lengths, max_itemsets)
    n_itemsets = int(lengths.sum())
    sizes = 1 + rng.poisson(max(mean_itemset_size - 1.0, 0.0),
                            size=n_itemsets)
    n_tokens = int(sizes.sum())

    wside = min(6, n_items)
    wsets = np.searchsorted(cdf, rng.random((n_sequences, wside)),
                            side="right")
    seq_of_itemset = np.repeat(np.arange(n_sequences), lengths)
    seq_of_token = np.repeat(seq_of_itemset, sizes)
    use_wset = rng.random(n_tokens) < correlation
    from_wset = wsets[seq_of_token, rng.integers(0, wside, size=n_tokens)]
    from_global = np.searchsorted(cdf, rng.random(n_tokens), side="right")
    # .tolist(): plain Python ints, the SequenceDB contract (np.int64 would
    # leak into json serialization paths)
    items = (np.where(use_wset, from_wset, from_global) + 1).tolist()

    # assemble: one cheap Python pass over itemset boundaries
    tok_bounds = np.concatenate(([0], np.cumsum(sizes))).tolist()
    set_bounds = np.concatenate(([0], np.cumsum(lengths))).tolist()
    itemsets = [tuple(sorted(set(items[tok_bounds[j]:tok_bounds[j + 1]])))
                for j in range(n_itemsets)]
    return [tuple(itemsets[set_bounds[i]:set_bounds[i + 1]])
            for i in range(n_sequences)]


# Shapes follow BASELINE.md "public dataset characteristics" (scaled variants
# for tests; full-size variants for bench.py).  ``fast=True`` routes through
# synthetic_db_fast (vectorized; different DBs for the same seed — see its
# docstring) for full-scale experiments.

def _generator(fast: bool):
    return synthetic_db_fast if fast else synthetic_db


def bms_webview1_like(seed: int = 1, scale: float = 1.0,
                      fast: bool = False) -> SequenceDB:
    return _generator(fast)(seed, int(59600 * scale), max(32, int(497 * scale)),
                            mean_itemsets=2.5, zipf_s=1.1)


def bms_webview2_like(seed: int = 2, scale: float = 1.0,
                      fast: bool = False) -> SequenceDB:
    return _generator(fast)(seed, int(77500 * scale), max(64, int(3300 * scale)),
                            mean_itemsets=4.6, zipf_s=1.15)


def msnbc_like(seed: int = 3, scale: float = 1.0,
               fast: bool = False) -> SequenceDB:
    # 17 page categories, long-tailed lengths.
    return _generator(fast)(seed, int(990000 * scale), 17,
                            mean_itemsets=5.7, zipf_s=0.9, max_itemsets=96)


def kosarak_like(seed: int = 4, scale: float = 1.0,
                 fast: bool = False) -> SequenceDB:
    return _generator(fast)(seed, int(990000 * scale),
                            max(128, int(41000 * scale)),
                            mean_itemsets=8.1, zipf_s=1.3)


def gazelle_like(seed: int = 5, scale: float = 1.0,
                 fast: bool = False) -> SequenceDB:
    return _generator(fast)(seed, int(59000 * scale), max(64, int(498 * scale)),
                            mean_itemsets=2.5, zipf_s=1.1)


def sub_crossover_db(offset: int = 0, n_seq: int = 200) -> SequenceDB:
    """Deterministic SUB-crossover shape for the engine planner
    (service/planner.py): ~400 items each in exactly 2 of ``n_seq``
    sequences (frequent-projection density at minsup 2 ~ 2/n_seq =
    0.01 < the 0.02 crossover; alphabet ~ 402 < the 512 ceiling), plus
    two shared marker items so the mine is non-trivial.  ``offset``
    rotates the item assignment for distinct-but-identically-shaped
    pools.  ONE definition — tests/test_planner.py, spam_smoke and
    ``bench_throughput --mix engines`` all pin routing against this
    shape, and a crossover retune must move them together."""
    db: SequenceDB = []
    for s in range(n_seq):
        a = 1000 + ((s + offset) % 200) * 2
        c = 1000 + ((s + offset + 50) % 200) * 2
        seq = [(a,), (a + 1,), (c,), (c + 1,)]
        if s % 16 == 0:
            seq = [(3 + offset,)] + seq + [(5 + offset,)]
        db.append(tuple(seq))
    return db
