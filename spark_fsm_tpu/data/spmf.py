"""SPMF sequence-format parser / writer.

The reference service mines sequence databases in the SPMF text format
(SURVEY.md sec 2.3): one sequence per line; itemsets are groups of
space-separated positive integer item ids; ``-1`` terminates an itemset;
``-2`` terminates the sequence.  Example::

    1 3 -1 2 -1 2 4 -2      # <{1,3},{2},{2,4}>

In-memory representation: a sequence database is ``list[Sequence]`` where
``Sequence = tuple[Itemset, ...]`` and ``Itemset = tuple[int, ...]`` with
items sorted ascending (SPMF guarantees sorted itemsets; we normalise anyway
so downstream bitmap construction and i-extension ordering are well-defined).
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterable, List, Optional, Tuple

Itemset = Tuple[int, ...]
Sequence = Tuple[Itemset, ...]
SequenceDB = List[Sequence]


def fingerprint_db(db: Iterable[Sequence]) -> str:
    """Content-addressed dataset fingerprint: a streaming sha256 over the
    canonical in-memory form (itemsets deduped + sorted by the parser),
    one sequence at a time — never materializing the whole text.

    Deliberately hashes CONTENT ONLY, not the source spelling: a FILE
    path, an INLINE payload, and a SYNTH generator that resolve to the
    same sequences produce the SAME fingerprint, which is exactly what
    lets the result-reuse tier (service/resultcache.py) serve one
    cached mine to every spelling of the data.  The checkpoint layer's
    engine fingerprints cover engine state; this covers the dataset
    dimension.
    """
    h = hashlib.sha256(b"fsm-db-v1\n")
    for seq in db:
        parts: List[str] = []
        for itemset in seq:
            parts.extend(str(i) for i in itemset)
            parts.append("-1")
        parts.append("-2\n")
        h.update(" ".join(parts).encode("ascii"))
    return h.hexdigest()


def file_validator(path: str,
                   sample_bytes: int = 65536) -> Optional[dict]:
    """Cheap immutability witness for a FILE artifact: mtime (ns) +
    size + a sha256 over a head/tail content sample.  Two calls that
    return EQUAL dicts prove — to the strength an immutable-artifact
    deployment needs — that the path still names the bytes it named
    before, without re-reading a multi-GB dataset.

    This is what lets the result-reuse tier (service/resultcache.py)
    resolve a FILE-spelling request's content fingerprint AT ADMISSION
    (unlocking dominance serving for the FILE spelling, ROADMAP 2b):
    the learned path→fingerprint mapping is trusted only while the
    validator matches; any mismatch — touched file, rewritten file,
    same-size in-place edit inside the sampled windows — falls back to
    the mutable path (coalesce-only), never serves stale results.  An
    adversarial same-mtime same-size edit OUTSIDE the sampled windows
    can defeat it, which is why it gates REUSE, never correctness of a
    cold mine.  None when the path cannot be statted/read (the caller
    degrades to the mutable path)."""
    try:
        st = os.stat(path)
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            h.update(fh.read(sample_bytes))
            if st.st_size > sample_bytes:
                # tail window, starting past the head so files up to
                # 2x sample_bytes are covered in full
                fh.seek(max(sample_bytes, st.st_size - sample_bytes))
                h.update(fh.read(sample_bytes))
        return {"mtime_ns": int(st.st_mtime_ns),
                "size": int(st.st_size),
                "sample": h.hexdigest()}
    except OSError:
        return None


def parse_spmf(text: str) -> SequenceDB:
    """Parse SPMF sequence format into a list of tuple-of-itemset sequences.

    Blank lines and comment/header lines (``#``, and ARFF-style ``@``/``%``
    headers found in SPMF-converted files) are skipped.  A line may omit the
    trailing ``-2``; a trailing ``-1`` before ``-2`` is optional.  Item ids
    must be positive integers.
    """
    db: SequenceDB = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith(("#", "@", "%")):
            continue
        seq: List[Itemset] = []
        cur: List[int] = []
        for tok in line.split():
            v = int(tok)
            if v == -2:
                break
            if v == -1:
                if cur:
                    seq.append(tuple(sorted(set(cur))))
                    cur = []
            else:
                if v <= 0:
                    raise ValueError(f"item ids must be positive, got {v!r} in line {line!r}")
                cur.append(v)
        if cur:
            seq.append(tuple(sorted(set(cur))))
        if seq:
            db.append(tuple(seq))
    return db


def format_spmf(db: Iterable[Sequence]) -> str:
    """Serialize a sequence database back to SPMF text (with -1/-2 markers)."""
    lines = []
    for seq in db:
        parts: List[str] = []
        for itemset in seq:
            parts.extend(str(i) for i in itemset)
            parts.append("-1")
        parts.append("-2")
        lines.append(" ".join(parts))
    return "\n".join(lines) + ("\n" if lines else "")


def load_spmf(path: str) -> SequenceDB:
    with open(path, "r", encoding="utf-8") as f:
        return parse_spmf(f.read())


def save_spmf(db: Iterable[Sequence], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(format_spmf(db))
