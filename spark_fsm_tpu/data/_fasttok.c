/* Native tokenizer for the data layer (L1).
 *
 * build_vertical's hot host path flattens a SequenceDB (a Python list of
 * tuples of tuples of ints) into token arrays; the pure-Python generator
 * chain costs ~6 s of the ~8 s vertical build at 990k sequences (5.6M
 * tokens).  This extension walks the object graph once in C and returns
 * the three arrays as raw little-endian buffers (~0.3 s for the same DB):
 *
 *   flatten(db) -> (lengths: bytes of int32[n_seq]   -- itemsets per seq,
 *                   counts:  bytes of int64[n_sets]  -- items per itemset,
 *                   items:   bytes of int64[n_toks]) -- item ids, in order
 *
 * The Python wrapper (data/fasttok.py) wraps them with np.frombuffer and
 * falls back to the numpy path whenever this module is unavailable --
 * byte-identical results either way (tested).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

static PyObject *
flatten(PyObject *self, PyObject *arg)
{
    PyObject *db = PySequence_Fast(arg, "db must be a sequence of sequences");
    if (db == NULL)
        return NULL;

    Py_ssize_t n_seq = PySequence_Fast_GET_SIZE(db);
    Py_ssize_t n_sets = 0, n_toks = 0;

    /* pass 1: sizes.  Container sizes are re-read every iteration before
     * each unchecked GET_ITEM macro read: PySequence_Size below can
     * re-enter Python (__len__), and a re-entrant callback shrinking a
     * borrowed list would otherwise turn GET_ITEM into a read past the
     * new size -- undefined behavior before any write guard exists. */
    for (Py_ssize_t i = 0; i < n_seq; i++) {
        if (i >= PySequence_Fast_GET_SIZE(db)) {
            PyErr_SetString(PyExc_RuntimeError,
                            "db changed size during tokenizer pass 1");
            goto fail_db;
        }
        PyObject *seq = PySequence_Fast(
            PySequence_Fast_GET_ITEM(db, i), "sequence must be a sequence");
        if (seq == NULL)
            goto fail_db;
        Py_ssize_t ns = PySequence_Fast_GET_SIZE(seq);
        /* totals feed n_sets*8 / n_toks*8 byte counts below: cap them so
         * a lying __len__ cannot overflow signed Py_ssize_t (UB) — the
         * same adversarial inputs the re-read guards handle get a clean
         * error here too */
        if (ns > PY_SSIZE_T_MAX / 8 - n_sets) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_OverflowError,
                            "tokenizer size totals overflow");
            goto fail_db;
        }
        n_sets += ns;
        for (Py_ssize_t j = 0; j < ns; j++) {
            if (j >= PySequence_Fast_GET_SIZE(seq)) {
                Py_DECREF(seq);
                PyErr_SetString(PyExc_RuntimeError,
                                "sequence changed size during tokenizer "
                                "pass 1");
                goto fail_db;
            }
            Py_ssize_t sz = PySequence_Size(PySequence_Fast_GET_ITEM(seq, j));
            if (sz < 0) {
                Py_DECREF(seq);
                goto fail_db;
            }
            if (sz > PY_SSIZE_T_MAX / 8 - n_toks) {
                Py_DECREF(seq);
                PyErr_SetString(PyExc_OverflowError,
                                "tokenizer size totals overflow");
                goto fail_db;
            }
            n_toks += sz;
        }
        Py_DECREF(seq);
    }

    PyObject *lengths = PyBytes_FromStringAndSize(NULL, n_seq * 4);
    PyObject *counts = PyBytes_FromStringAndSize(NULL, n_sets * 8);
    PyObject *items = PyBytes_FromStringAndSize(NULL, n_toks * 8);
    if (lengths == NULL || counts == NULL || items == NULL)
        goto fail_bufs;

    int32_t *lp = (int32_t *)PyBytes_AS_STRING(lengths);
    int64_t *cp = (int64_t *)PyBytes_AS_STRING(counts);
    int64_t *ip = (int64_t *)PyBytes_AS_STRING(items);
    /* Pass-2 sizes can disagree with pass 1 for adversarial inputs (a
     * lazy sequence whose __len__ lies, or Python code re-entered via an
     * item's __index__ mutating the db) — every write is bounds-checked
     * against the pass-1 totals so a mismatch raises instead of
     * corrupting the heap or returning garbage tails. */
    int32_t *lp_end = lp + n_seq;
    int64_t *cp_end = cp + n_sets;
    int64_t *ip_end = ip + n_toks;

    /* pass 2: fill.  Same re-read-before-GET_ITEM discipline as pass 1
     * (here PyLong_AsLongLong can re-enter via an item's __index__);
     * size drift bails to fail_mutated like the write guards. */
    for (Py_ssize_t i = 0; i < n_seq; i++) {
        if (i >= PySequence_Fast_GET_SIZE(db))
            goto fail_mutated;
        PyObject *seq = PySequence_Fast(
            PySequence_Fast_GET_ITEM(db, i), "sequence must be a sequence");
        if (seq == NULL)
            goto fail_bufs;
        Py_ssize_t ns = PySequence_Fast_GET_SIZE(seq);
        if (lp >= lp_end || cp + ns > cp_end) {
            Py_DECREF(seq);
            goto fail_mutated;
        }
        *lp++ = (int32_t)ns;
        for (Py_ssize_t j = 0; j < ns; j++) {
            if (j >= PySequence_Fast_GET_SIZE(seq)) {
                Py_DECREF(seq);
                goto fail_mutated;
            }
            PyObject *iset = PySequence_Fast(
                PySequence_Fast_GET_ITEM(seq, j), "itemset must be a sequence");
            if (iset == NULL) {
                Py_DECREF(seq);
                goto fail_bufs;
            }
            Py_ssize_t sz = PySequence_Fast_GET_SIZE(iset);
            if (ip + sz > ip_end) {
                Py_DECREF(iset);
                Py_DECREF(seq);
                goto fail_mutated;
            }
            *cp++ = (int64_t)sz;
            for (Py_ssize_t k = 0; k < sz; k++) {
                if (k >= PySequence_Fast_GET_SIZE(iset)) {
                    Py_DECREF(iset);
                    Py_DECREF(seq);
                    goto fail_mutated;
                }
                int64_t v = PyLong_AsLongLong(
                    PySequence_Fast_GET_ITEM(iset, k));
                if (v == -1 && PyErr_Occurred()) {
                    Py_DECREF(iset);
                    Py_DECREF(seq);
                    goto fail_bufs;
                }
                *ip++ = v;
            }
            Py_DECREF(iset);
        }
        Py_DECREF(seq);
    }
    if (lp != lp_end || cp != cp_end || ip != ip_end)
        goto fail_mutated;  /* under-filled: garbage tails, refuse */

    Py_DECREF(db);
    PyObject *out = PyTuple_Pack(3, lengths, counts, items);
    Py_DECREF(lengths);
    Py_DECREF(counts);
    Py_DECREF(items);
    return out;

fail_mutated:
    PyErr_SetString(PyExc_RuntimeError,
                    "db changed size between tokenizer passes");
fail_bufs:
    Py_XDECREF(lengths);
    Py_XDECREF(counts);
    Py_XDECREF(items);
fail_db:
    Py_DECREF(db);
    return NULL;
}

static PyMethodDef methods[] = {
    {"flatten", flatten, METH_O,
     "flatten(db) -> (lengths_i32_bytes, counts_i64_bytes, items_i64_bytes)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fasttok", NULL, -1, methods,
};

PyMODINIT_FUNC
PyInit__fasttok(void)
{
    return PyModule_Create(&moduledef);
}
