"""Lazy loader for the native SequenceDB tokenizer (_fasttok.c).

The extension is built ON DEMAND with the system compiler into a per-user
cache (first call only; subsequent processes dlopen the cached .so) — no
install step, no build-time dependency, and every failure path (no
compiler, no headers, unsupported platform, ``SPARKFSM_FASTTOK=0``) falls
back silently to build_vertical's numpy flatten with byte-identical
results.  This is the framework's native L1 component: the reference's
data prep ran distributed on Spark executors; here the per-host tokenize
is a single C pass instead of a Python generator chain (~20x on a
990k-sequence DB).
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)
_mod = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "_fasttok.c")


def _so_path() -> str:
    cache = os.path.join(os.path.expanduser("~"), ".cache", "spark_fsm_tpu")
    os.makedirs(cache, exist_ok=True)
    tag = f"cp{sys.version_info.major}{sys.version_info.minor}"
    # content hash in the name: a changed _fasttok.c always recompiles
    # (mtime comparisons break under reproducible-build installs whose
    # files carry epoch timestamps)
    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(cache, f"_fasttok-{tag}-{h}.so")


def _build(so: str) -> None:
    inc = sysconfig.get_paths()["include"]
    tmp = f"{so}.{os.getpid()}.tmp"
    subprocess.run(
        ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}", _SRC, "-o", tmp],
        check=True, capture_output=True, timeout=120)
    os.replace(tmp, so)  # atomic: concurrent builders race safely


def _load():
    global _mod, _tried
    if _tried:
        return _mod
    _tried = True
    if os.environ.get("SPARKFSM_FASTTOK") == "0":
        return None
    try:
        so = _so_path()
        if not os.path.exists(so):
            _build(so)
        spec = importlib.util.spec_from_file_location("_fasttok", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mod = mod
    except Exception as exc:
        _log.info("native tokenizer unavailable (%s: %s); using the numpy "
                  "flatten", type(exc).__name__, exc)
        _mod = None
    return _mod


def flatten(db) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(seq_lengths int32, itemset_counts int64, raw_items int64) for a
    SequenceDB, or None when the extension is unavailable (callers keep
    their numpy path).  Arrays are read-only views over the C buffers."""
    mod = _load()
    if mod is None:
        return None
    lengths_b, counts_b, items_b = mod.flatten(db)
    return (np.frombuffer(lengths_b, np.int32),
            np.frombuffer(counts_b, np.int64),
            np.frombuffer(items_b, np.int64))


def flatten_numpy(db) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The pure-numpy flatten — the semantics the C extension must match
    byte for byte (the fallback build_vertical uses, and the reference
    the parity test compares against)."""
    lengths = np.fromiter((len(s) for s in db), np.int32, count=len(db))
    counts = np.fromiter((len(iset) for s in db for iset in s), np.int64)
    items = np.fromiter((it for s in db for iset in s for it in iset),
                        np.int64)
    return lengths, counts, items
