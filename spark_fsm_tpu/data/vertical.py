"""Vertical bitmap sequence database (SPAM-style id-lists).

SURVEY.md sec 2.3 step 1: one pass over the horizontal DB builds, per item,
an id-list of (sequence-id, itemset-position) pairs.  We use the bitmap
representation (the variant the north star maps to TPU): for each item a
``[n_seq, n_words]`` uint32 bitmap where bit ``p`` of sequence ``s`` (word
``p // 32``, bit ``p % 32``, LSB-first) is set iff the item occurs in itemset
``p`` of sequence ``s``.

Positions are the *original* itemset indices of each sequence — the
frequent-item projection drops bitmap rows but never renumbers positions, so
maxgap/maxwindow constraints (which are defined on itemset positions,
SURVEY.md sec 2.3 step 6) see the same gaps with or without projection.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from spark_fsm_tpu.data.spmf import SequenceDB

WORD_BITS = 32


@dataclasses.dataclass
class VerticalDB:
    """Dense vertical bitmap database over the frequent-item projection.

    The authoritative representation is the token table — one row per
    (kept-item occurrence): ``tok_item`` (dense item index), ``tok_seq``,
    ``tok_word``/``tok_mask`` (bit address of the itemset position).  It is
    ~1000x smaller than the dense bitmaps, so device engines upload tokens
    and scatter-build the bitmap store IN HBM instead of pushing hundreds of
    MB over PCIe/tunnel; CPU consumers use the lazily-built dense ``bitmaps``.

    Attributes:
      item_ids:   [n_items] int32, original SPMF item ids, strictly ascending.
                  Bitmap row ``i`` belongs to item ``item_ids[i]``.
      seq_lengths:[n_seq] int32, number of itemsets per sequence.
      n_positions: padded position capacity = n_words * 32 (>= max seq length).
      item_supports: [n_items] int32 sequence-support of each kept item.
      tok_*: [n_tokens] int32/uint32 token table (see above).
      bitmaps: [n_items, n_seq, n_words] uint32 occurrence bitmaps (lazy).
    """

    item_ids: np.ndarray
    seq_lengths: np.ndarray
    n_positions: int
    item_supports: np.ndarray
    tok_item: np.ndarray
    tok_seq: np.ndarray
    tok_word: np.ndarray
    tok_mask: np.ndarray
    _n_seq: int
    _n_words: int
    _bitmaps: Optional[np.ndarray] = None

    @property
    def n_items(self) -> int:
        return int(self.item_ids.shape[0])

    @property
    def n_sequences(self) -> int:
        return self._n_seq

    @property
    def n_words(self) -> int:
        return self._n_words

    @property
    def bitmaps(self) -> np.ndarray:
        """Dense [n_items, n_seq, n_words] bitmaps, built on first use."""
        if self._bitmaps is None:
            bm = np.zeros(self.n_items * self._n_seq * self._n_words, np.uint32)
            flat = (self.tok_item.astype(np.int64) * self._n_seq
                    + self.tok_seq) * self._n_words + self.tok_word
            # distinct (seq,pos) per item occurrence => add == bitwise OR
            np.add.at(bm, flat, self.tok_mask)
            self._bitmaps = bm.reshape(self.n_items, self._n_seq, self._n_words)
        return self._bitmaps

    def nbytes(self) -> int:
        return self.n_items * self._n_seq * self._n_words * 4

    # ---------------------------------------------------- id-list view
    # The token table is item-major (build_vertical sorts by
    # (item, seq, pos) via the np.unique dedup key), so each item's
    # SPADE-style id-list is a contiguous slice — the sparse half of
    # the hybrid vertical store reads these slices instead of ever
    # materializing the item's dense bitmap row.

    @property
    def _tok_ptr(self) -> np.ndarray:
        """[n_items + 1] row pointer into the item-major token table."""
        ptr = getattr(self, "_tok_ptr_cache", None)
        if ptr is None:
            ptr = np.searchsorted(
                self.tok_item, np.arange(self.n_items + 1, dtype=np.int64))
            self._tok_ptr_cache = ptr
        return ptr

    def idlist(self, i: int):
        """Item ``i``'s id-list: (tok_seq, tok_word, tok_mask) slices,
        one entry per (sequence, position) occurrence."""
        ptr = self._tok_ptr
        lo, hi = int(ptr[i]), int(ptr[i + 1])
        return self.tok_seq[lo:hi], self.tok_word[lo:hi], self.tok_mask[lo:hi]

    def idlist_lengths(self) -> np.ndarray:
        """[n_items] int64 token count per item (id-list sizes)."""
        return np.diff(self._tok_ptr)


def idlist_join_support(prefix_bitmap: np.ndarray, tok_seq: np.ndarray,
                        tok_word: np.ndarray, tok_mask: np.ndarray) -> int:
    """Support of ``prefix AND item`` evaluated AGAINST THE ID-LIST —
    the sparse-representation join: a token survives iff the prefix
    bitmap (pass the plain row for an i-extension, the
    ``sext_transform``-ed row for an s-extension) has its bit set, and
    the support is the count of distinct sequences with any survivor.
    Byte-identical to ``support(prefix & bitmaps[i])`` by construction
    (pinned in tests/test_vertical.py) without touching the
    ``n_seq * n_words`` dense row — work scales with the item's token
    count, which is what makes the id-list side of the density
    crossover win on sparse items."""
    hit = (prefix_bitmap[tok_seq, tok_word] & tok_mask) != 0
    return int(np.unique(tok_seq[hit]).size)


# ---------------------------------------------------------------------------
# Per-item representation plan (the hybrid vertical store's routing table)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RepPlan:
    """Per-item vertical-representation choice for one mine.

    ``rep[i]`` True holds item ``i`` as a dense SPAM bitmap row (wave
    lane); False holds it as a SPADE id-list (sparse pair-path lane).
    Built by :func:`rep_plan` from per-item densities against the
    planner's calibrated crossover; ``pin`` records whether the split
    was density-routed ("auto") or operator-pinned ("bitmap"/"idlist"
    force a uniform store — the debugging/bench fixed-representation
    modes).  Result bytes are representation-invariant: the plan only
    picks which evaluation path computes each (identical) support."""

    rep: np.ndarray          # [n_items] bool, True = dense bitmap
    densities: np.ndarray    # [n_items] float64 item support / n_seq
    crossover: float
    pin: str                 # "auto" | "bitmap" | "idlist"

    @property
    def n_dense(self) -> int:
        return int(np.count_nonzero(self.rep))

    @property
    def n_sparse(self) -> int:
        return int(self.rep.size) - self.n_dense

    @property
    def hybrid(self) -> bool:
        return self.n_sparse > 0

    def as_attrs(self) -> dict:
        """Flat numeric/str summary for the planner trace span."""
        d = self.densities
        return {
            "representation": self.pin,
            "density_crossover": round(float(self.crossover), 6),
            "dense_items": self.n_dense,
            "idlist_items": self.n_sparse,
            "min_item_density": round(float(d.min()), 6) if d.size else 0.0,
            "max_item_density": round(float(d.max()), 6) if d.size else 0.0,
        }


def rep_plan(item_supports: np.ndarray, n_sequences: int, *,
             crossover: float, pin: str = "auto") -> RepPlan:
    """Pick a vertical representation PER ITEM: density (the item's
    sequence-support over the sequence axis — exactly the fill fraction
    of its dense bitmap row and the per-item spelling of
    ``DatasetStats.density``) at or above the crossover routes to the
    SPAM bitmap, below it to the SPADE id-list.  ``pin`` forces a
    uniform store for debugging/benches."""
    sup = np.asarray(item_supports, dtype=np.int64)
    d = sup / float(max(1, int(n_sequences)))
    if pin == "bitmap":
        rep = np.ones(sup.shape, dtype=bool)
    elif pin == "idlist":
        rep = np.zeros(sup.shape, dtype=bool)
    elif pin == "auto":
        rep = d >= float(crossover)
    else:
        raise ValueError(
            f"representation must be auto|bitmap|idlist, got {pin!r}")
    return RepPlan(rep=rep, densities=d, crossover=float(crossover), pin=pin)


def build_vertical(
    db: SequenceDB,
    min_item_support: int = 1,
    pad_sequences_to: Optional[int] = None,
    word_multiple: int = 1,
) -> VerticalDB:
    """Build the vertical bitmap DB, keeping only items with sequence-support
    >= ``min_item_support`` (the frequent-item projection: infrequent items
    can never appear in a frequent pattern, so their rows are dropped;
    positions are NOT renumbered).

    ``pad_sequences_to`` pads the sequence axis (extra all-zero sequences)
    e.g. to a device-mesh multiple; padded sequences contribute no support.
    ``word_multiple`` pads n_words up (e.g. for kernel block shapes).
    """
    n_seq = len(db)
    if n_seq == 0:
        raise ValueError("empty sequence database")

    # One pass flattens the DB to token arrays; everything after is
    # vectorized numpy (the reference's one-pass vertical-db
    # construction, SURVEY.md sec 2.3 step 1).  The native tokenizer
    # (data/_fasttok.c) does the pass in C when available — the Python
    # generator chain is ~6 of the ~8 s vertical build at 990k
    # sequences — with this numpy path as the always-correct fallback.
    from spark_fsm_tpu.data import fasttok

    ft = fasttok.flatten(db)
    if ft is None:
        ft = fasttok.flatten_numpy(db)
    seq_lengths, counts, raw_items = ft
    n_itemsets_total = len(counts)
    # position (itemset index within its sequence) per itemset, then per token
    seq_of_itemset = np.repeat(np.arange(n_seq, dtype=np.int64), seq_lengths)
    starts = np.concatenate(([0], np.cumsum(seq_lengths)))[seq_of_itemset]
    pos_of_itemset = np.arange(n_itemsets_total, dtype=np.int64) - starts
    tok_seq = np.repeat(seq_of_itemset, counts)
    tok_pos = np.repeat(pos_of_itemset, counts)

    max_len = int(seq_lengths.max())
    n_words = max(1, -(-max_len // WORD_BITS))
    if word_multiple > 1:
        n_words = -(-n_words // word_multiple) * word_multiple

    # Sequence-support per item: count unique (item, seq) pairs.
    pair = raw_items * n_seq + tok_seq
    uniq_pair = np.unique(pair)
    uniq_item = uniq_pair // n_seq
    items_all, sup_all = np.unique(uniq_item, return_counts=True)
    keep = sup_all >= min_item_support
    kept = items_all[keep]
    item_supports = sup_all[keep].astype(np.int32)
    n_items = len(kept)

    # Remap raw item ids -> dense kept index; drop tokens of dropped items.
    idx = np.searchsorted(kept, raw_items)
    idx_clip = np.minimum(idx, max(n_items - 1, 0))
    if n_items == 0:
        tok_keep = np.zeros(len(raw_items), dtype=bool)
    else:
        tok_keep = kept[idx_clip] == raw_items
    tok_item = idx_clip[tok_keep]
    tok_seq_k = tok_seq[tok_keep]
    tok_pos_k = tok_pos[tok_keep]
    # Dedup (item, seq, pos) — a caller-built DB may repeat an item inside
    # an itemset, and the scatter-ADD consumers (here and the device store
    # build) rely on each token being a distinct bit.
    key = (tok_item * n_seq + tok_seq_k) * (np.int64(n_words) * WORD_BITS) + tok_pos_k
    uniq = np.unique(key)
    tok_pos_k = uniq % (np.int64(n_words) * WORD_BITS)
    rest = uniq // (np.int64(n_words) * WORD_BITS)
    tok_seq_k = (rest % n_seq).astype(np.int32)
    tok_item = (rest // n_seq).astype(np.int32)
    tok_word = (tok_pos_k // WORD_BITS).astype(np.int32)
    tok_mask = (np.uint32(1) << (tok_pos_k % WORD_BITS).astype(np.uint32))

    n_seq_padded = n_seq if pad_sequences_to is None else max(n_seq, pad_sequences_to)
    seq_lengths_padded = np.zeros(n_seq_padded, dtype=np.int32)
    seq_lengths_padded[:n_seq] = seq_lengths
    return VerticalDB(
        item_ids=kept.astype(np.int32),
        seq_lengths=seq_lengths_padded,
        n_positions=n_words * WORD_BITS,
        item_supports=item_supports,
        tok_item=tok_item,
        tok_seq=tok_seq_k,
        tok_word=tok_word,
        tok_mask=tok_mask,
        _n_seq=n_seq_padded,
        _n_words=n_words,
    )


@dataclasses.dataclass(frozen=True)
class DatasetStats:
    """Shape/density summary of a SequenceDB — the engine planner's
    input (service/planner.py).  Computed with the same one-pass token
    flatten the vertical build uses, so "density" here means exactly
    what it means to the engines: how full the vertical bitmaps are.

    ``alphabet``/``density`` are computed over the FREQUENT-ITEM
    PROJECTION at ``min_item_support`` (1 = the raw alphabet) because
    that is the item axis the engines actually build: ``alphabet`` is
    the surviving item count and ``density`` is distinct (item,
    sequence) occurrence pairs over ``alphabet * n_sequences`` — the
    expected fraction of sequences a surviving item occurs in, i.e.
    the expected fill of the vertical bitmaps and the expected
    fraction of the item axis alive per candidate node.  High density
    means per-node candidate lists approach the full (projected)
    alphabet, which is the regime where SPAM's fixed-shape all-items
    wave beats ragged candidate-list packing.
    """

    n_sequences: int
    n_itemsets: int
    n_tokens: int
    alphabet: int
    max_len: int
    avg_len: float
    n_words: int
    density: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def dataset_stats(db: SequenceDB,
                  min_item_support: int = 1) -> DatasetStats:
    """One cheap vectorized pass (data/fasttok) over the horizontal DB;
    no bitmap is materialized.  ``min_item_support`` applies the same
    frequent-item projection ``build_vertical`` will — the planner
    passes the request's absolute minsup so the density it routes on
    is the density the engine will actually mine."""
    n_seq = len(db)
    if n_seq == 0:
        return DatasetStats(0, 0, 0, 0, 0, 0.0, 1, 0.0)
    from spark_fsm_tpu.data import fasttok

    ft = fasttok.flatten(db)
    if ft is None:
        ft = fasttok.flatten_numpy(db)
    seq_lengths, counts, raw_items = ft
    n_itemsets = int(len(counts))
    n_tokens = int(len(raw_items))
    max_len = int(seq_lengths.max())
    n_words = max(1, -(-max_len // WORD_BITS))
    alphabet = 0
    density = 0.0
    if n_tokens:
        seq_of_itemset = np.repeat(np.arange(n_seq, dtype=np.int64),
                                   seq_lengths)
        tok_seq = np.repeat(seq_of_itemset, counts)
        uniq_pair = np.unique(raw_items.astype(np.int64) * n_seq
                              + tok_seq)
        _, sup_all = np.unique(uniq_pair // n_seq, return_counts=True)
        kept = sup_all >= max(1, int(min_item_support))
        alphabet = int(kept.sum())
        if alphabet:
            density = int(sup_all[kept].sum()) / float(alphabet * n_seq)
    return DatasetStats(
        n_sequences=n_seq, n_itemsets=n_itemsets, n_tokens=n_tokens,
        alphabet=alphabet, max_len=max_len,
        avg_len=round(n_itemsets / n_seq, 4), n_words=n_words,
        density=round(density, 6))


def abs_minsup(rel_minsup: float, n_sequences: int) -> int:
    """Relative minsup (e.g. 0.001 = 0.1%) -> absolute sequence count.

    SURVEY.md sec 2.3: ``ceil(minsup * |DB|)``, floored at 1.
    """
    return max(1, int(np.ceil(rel_minsup * n_sequences)))
