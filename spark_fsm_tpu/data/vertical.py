"""Vertical bitmap sequence database (SPAM-style id-lists).

SURVEY.md sec 2.3 step 1: one pass over the horizontal DB builds, per item,
an id-list of (sequence-id, itemset-position) pairs.  We use the bitmap
representation (the variant the north star maps to TPU): for each item a
``[n_seq, n_words]`` uint32 bitmap where bit ``p`` of sequence ``s`` (word
``p // 32``, bit ``p % 32``, LSB-first) is set iff the item occurs in itemset
``p`` of sequence ``s``.

Positions are the *original* itemset indices of each sequence — the
frequent-item projection drops bitmap rows but never renumbers positions, so
maxgap/maxwindow constraints (which are defined on itemset positions,
SURVEY.md sec 2.3 step 6) see the same gaps with or without projection.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from spark_fsm_tpu.data.spmf import SequenceDB

WORD_BITS = 32


@dataclasses.dataclass
class VerticalDB:
    """Dense vertical bitmap database over the frequent-item projection.

    Attributes:
      item_ids:   [n_items] int32, original SPMF item ids, strictly ascending.
                  Bitmap row ``i`` belongs to item ``item_ids[i]``.
      bitmaps:    [n_items, n_seq, n_words] uint32 occurrence bitmaps.
      seq_lengths:[n_seq] int32, number of itemsets per sequence.
      n_positions: padded position capacity = n_words * 32 (>= max seq length).
      item_supports: [n_items] int32 sequence-support of each kept item.
    """

    item_ids: np.ndarray
    bitmaps: np.ndarray
    seq_lengths: np.ndarray
    n_positions: int
    item_supports: np.ndarray

    @property
    def n_items(self) -> int:
        return int(self.bitmaps.shape[0])

    @property
    def n_sequences(self) -> int:
        return int(self.bitmaps.shape[1])

    @property
    def n_words(self) -> int:
        return int(self.bitmaps.shape[2])

    def nbytes(self) -> int:
        return int(self.bitmaps.nbytes)


def build_vertical(
    db: SequenceDB,
    min_item_support: int = 1,
    pad_sequences_to: Optional[int] = None,
    word_multiple: int = 1,
) -> VerticalDB:
    """Build the vertical bitmap DB, keeping only items with sequence-support
    >= ``min_item_support`` (the frequent-item projection: infrequent items
    can never appear in a frequent pattern, so their rows are dropped;
    positions are NOT renumbered).

    ``pad_sequences_to`` pads the sequence axis (extra all-zero sequences)
    e.g. to a device-mesh multiple; padded sequences contribute no support.
    ``word_multiple`` pads n_words up (e.g. for kernel block shapes).
    """
    n_seq = len(db)
    if n_seq == 0:
        raise ValueError("empty sequence database")
    seq_lengths = np.array([len(s) for s in db], dtype=np.int32)
    max_len = int(seq_lengths.max())
    n_words = max(1, -(-max_len // WORD_BITS))
    if word_multiple > 1:
        n_words = -(-n_words // word_multiple) * word_multiple

    # Pass 1: sequence-support per item (count each item once per sequence).
    supports: dict[int, int] = {}
    for seq in db:
        seen = set()
        for itemset in seq:
            seen.update(itemset)
        for it in seen:
            supports[it] = supports.get(it, 0) + 1
    kept = sorted(it for it, sup in supports.items() if sup >= min_item_support)
    item_index = {it: i for i, it in enumerate(kept)}
    n_items = len(kept)

    n_seq_padded = n_seq if pad_sequences_to is None else max(n_seq, pad_sequences_to)
    bitmaps = np.zeros((n_items, n_seq_padded, n_words), dtype=np.uint32)

    # Pass 2: set occurrence bits.
    for s, seq in enumerate(db):
        for p, itemset in enumerate(seq):
            word = p // WORD_BITS
            mask = np.uint32(1 << (p % WORD_BITS))
            for it in itemset:
                i = item_index.get(it)
                if i is not None:
                    bitmaps[i, s, word] |= mask

    seq_lengths_padded = np.zeros(n_seq_padded, dtype=np.int32)
    seq_lengths_padded[:n_seq] = seq_lengths
    item_supports = np.array([supports[it] for it in kept], dtype=np.int32)
    return VerticalDB(
        item_ids=np.array(kept, dtype=np.int32),
        bitmaps=bitmaps,
        seq_lengths=seq_lengths_padded,
        n_positions=n_words * WORD_BITS,
        item_supports=item_supports,
    )


def abs_minsup(rel_minsup: float, n_sequences: int) -> int:
    """Relative minsup (e.g. 0.001 = 0.1%) -> absolute sequence count.

    SURVEY.md sec 2.3: ``ceil(minsup * |DB|)``, floored at 1.
    """
    return max(1, int(np.ceil(rel_minsup * n_sequences)))
