"""Data layer: SPMF-format IO, vertical bitmap DB, sources, synthetic data."""
