"""Boot configuration — the reference's Configuration/application.conf analog.

The reference loads service host/port, Spark properties, and Redis/ES
endpoints from a Typesafe Config file at boot (SURVEY.md sec 1 L0, sec 5
config row); per-request knobs stay in the request's string map.  The
rebuild keeps that split: this module owns the boot-time knobs — service
address, store backend, device-mesh size, engine memory/batching budgets,
profiler output — loaded from a TOML or JSON file, while ``ServiceRequest``
carries the per-job vocabulary (``algorithm``, ``support``, ...).

File format (TOML shown; JSON with the same nesting also accepted):

    profile_dir = "traces"          # jax.profiler output root ("" = off)
    fault_injection = false         # allow /admin/faults (chaos lab) — the
                                    # endpoint is refused unless true

    [service]
    host = "0.0.0.0"
    port = 9000
    miner_workers = 2
    remote_port = 0                 # actor-protocol TCP entry (0 = off)
    job_retries = 1                 # failed-job re-runs before 'failure'
    queue_depth = 256               # bounded admission queue: submits past
                                    # this many queued jobs shed with HTTP
                                    # 429 + Retry-After (0 = unbounded)

    [store]
    backend = "inproc"              # or "redis"
    host = "127.0.0.1"
    port = 6379
    timeout_s = 10.0                # redis socket timeout (transport
                                    # failures past it surface as OSError
                                    # — what the storeguard probe reads)

    [storeguard]
    enabled = false                 # store-outage survival (service/
                                    # storeguard.py): health state machine
                                    # + write-behind durability spool +
                                    # outage-aware lease stalls; off = one
                                    # `is None` read per durable write
    probe_every_s = 1.0             # active store probe cadence while
                                    # unhealthy (0 = manual ticks, tests)
    down_after = 1                  # consecutive transport failures before
                                    # the probe is consulted for DOWN —
                                    # 1 (default) probes on the FIRST
                                    # failure, so an outage never burns a
                                    # job's retry budget before it is
                                    # proven; raise to probe lazier
    spool_max_entries = 512         # per-job write-behind spool bound;
                                    # overflow fences the job (terminal)
    stall_max_s = 120.0             # longest a job may stall at a safe
                                    # point waiting out an outage before
                                    # it conservatively self-fences
                                    # (0 = stall as long as the outage)
    ephemeral_admission = false     # admit loudly-flagged no-journal jobs
                                    # during an outage instead of 429

    [distributed]
    enabled = false                 # true: jax.distributed.initialize at boot
    coordinator_address = ""        # "" = JAX env vars / cloud auto-detect
    # num_processes / process_id: omit for env-var/cloud auto-detect

    [cluster]
    enabled = false                 # lease-fenced multi-replica mode: N
                                    # service replicas safely share ONE
                                    # Redis namespace (service/lease.py)
    replica_id = ""                 # "" = generated per boot (REQUIRED
                                    # unique per replica if set manually)
    lease_ttl_s = 10.0              # per-job lease TTL; a crashed
                                    # replica's jobs are adoptable after
                                    # at most this long
    heartbeat_s = 0.0               # renewal/heartbeat cadence
                                    # (0 = lease_ttl_s / 3)
    steal = true                    # idle replicas claim queued jobs
                                    # from loaded peers
    recover_every_s = 0.0           # periodic orphan-recovery cadence
                                    # (0 = lease_ttl_s)

    [engine]
    mesh_devices = 8                # 0 = single chip (no mesh)
    pool_bytes = 2147483648         # HBM slot-pool budget (default: adaptive, 35% of device HBM)
    node_batch = 256                # DFS nodes per device dispatch (default 1024, clamped to the pool)
    pipeline_depth = 4              # in-flight support readbacks
    chunk = 256                     # SPADE support-count batch width
    recompute_chunk = 256
    tsr_chunk = 2048                # TSR candidate batch (default adaptive)
    item_cap = 256                  # TSR iterative-deepening width
    fused = "auto"                  # SPADE routing: auto / always / never
                                    # / queue / dense (engine pins)
    watchdog_slack = 20.0           # dispatch watchdog: deadline = max(
                                    # watchdog_floor_s, estimate x slack);
                                    # omit to disable (utils/watchdog.py)
    watchdog_floor_s = 2.0

    [observability]
    trace = false                   # per-job flight recorder (utils/obs.py);
                                    # off = one global read per probe
    trace_max_spans = 512           # completed-span ring per job
    trace_jobs = 16                 # job traces kept (oldest evicted)
    spine_flush_spans = 32          # spans buffered per trace before an
                                    # automatic durable-spine flush
                                    # (cluster mode; terminal paths and
                                    # checkpoint saves always flush)
    spine_max_chunks = 256          # fsm:trace:{uid} retention: newest
                                    # N chunks kept (0 = unbounded)
    slo_window_s = 300.0            # /admin/slo sliding window

    [fusion]
    enabled = false                 # cross-job launch fusion broker
                                    # (service/fusion.py); off = one global
                                    # read per dispatch probe
    window_ms = 4.0                 # bounded fusion window: how long a
                                    # normal/low wave may wait for peers
    max_jobs = 8                    # waves co-scheduled into one launch
    max_width = 16384               # fused candidate-lane ceiling (pow2)
    dispatch_workers = 2            # broker dispatcher threads (matured
                                    # groups run concurrently)

    [partition]
    enabled = false                 # equivalence-class partitioned mining
                                    # (parallel/partition.py): split the
                                    # candidate frontier over the outer
                                    # axis of a 2-D parts x seq mesh
    parts = 0                       # partitions (0 = auto: one per
                                    # process in a multi-controller run,
                                    # else 2 when the mesh has >= 2
                                    # devices, else off)
    classes = 64                    # km-prefix hash buckets balanced
                                    # over the partitions

    [rescache]
    enabled = false                 # result-reuse tier above admission
                                    # (service/resultcache.py): content-
                                    # addressed dataset fingerprints,
                                    # in-flight request coalescing, and
                                    # dominance-based cache serving; off
                                    # = one attribute read per submit
    max_bytes = 67108864            # LRU byte budget for cached result
                                    # entries (0 = unbounded)
    coalesce = true                 # attach identical in-flight requests
                                    # as followers of one execution
    dominance = true                # serve dominated requests by host-
                                    # side filtering of cached results

    [fairness]
    enabled = false                 # weighted-fair multi-tenant admission
                                    # (service/fairness.py): DRR across
                                    # tenants within each priority class
    tenant_depth = 64               # per-tenant queued-job cap (0 = none)
    max_tenants = 64                # bounded live tenant vocabulary
    default_weight = 1.0            # weight for tenants not listed below
    [fairness.weights]              # tenant -> relative weight
    # gold = 4.0
    # free = 1.0

    [autoscale]
    enabled = false                 # elastic control plane (service/
                                    # autoscale.py); requires [cluster]
    min_replicas = 1
    max_replicas = 8
    up_queue_per_worker = 2.0       # scale up past this queued/worker
    up_p99_s = 0.0                  # scale up past this SLO p99 (0 = off)
    up_rate_derivative = 0.0        # PREDICTIVE scale-up: EWMA of the
                                    # fleet admission-rate derivative
                                    # (jobs/s per second) above which
                                    # load is accelerating (0 = off);
                                    # rides the same hold_s hysteresis
    rate_alpha = 0.3                # EWMA smoothing for the admission
                                    # rate and its derivative, in (0,1]
    down_free_frac = 0.5            # scale down past this idle fraction
    hold_s = 10.0                   # signal must persist (hysteresis)
    cooldown_s = 30.0               # min gap between decisions
    decide_every_s = 0.0            # controller cadence (0 = ttl/3)
    leader_ttl_s = 3.0              # fsm:autoscale:leader lease TTL
    drain_timeout_s = 60.0          # drain wait before exiting anyway

    [planner]
    mode = "auto"                   # engine planner (service/planner.py)
                                    # for algorithm=AUTO requests:
                                    # "auto" = density-crossover routing,
                                    # "pinned" = always route AUTO to the
                                    # engine below
    pinned = "SPADE_TPU"            # the engine AUTO resolves to under
                                    # pinned mode
    density_crossover = 0.02        # route patterns-AUTO to SPAM_TPU at
                                    # dataset density >= this (distinct
                                    # (item,seq) pairs / (alphabet*seqs);
                                    # calibrated — docs/DESIGN.md)
    max_alphabet = 512              # SPAM eligibility ceiling on the
                                    # frequent-alphabet width
    representation = "auto"         # per-ITEM vertical store within a
                                    # mine: "auto" = density crossover
                                    # picks bitmap (dense) vs id-list
                                    # (sparse) per item; "bitmap"/
                                    # "idlist" pin a uniform store
                                    # (debugging/bench lever)
    diffset_depth = 3               # pattern length at which supports
                                    # switch to the dEclat diffset
                                    # formulation (parent_support -
                                    # |diffset|); 0 disables

    [prewarm]
    enabled = true                  # AOT-compile the declared envelope at boot
    sequences = 77500               # expected dataset scale
    items = 384                     # expected frequent-projection width
    words = 1
    stream_batch_sequences = 99000  # per-push micro-batch size (0 = skip)
    stream_items = 256
    stream_seq_floor = 99000        # pin early pushes to the steady bucket

Unknown keys are rejected (a typo'd knob must not silently no-op).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ServiceConfig:
    host: str = "127.0.0.1"
    port: int = 9000
    miner_workers: int = 1
    remote_port: int = 0  # actor-protocol TCP entry (0 = disabled)
    job_retries: int = 1  # re-runs of a failed train job before 'failure'
    queue_depth: int = 256  # admission-queue bound: queued (not yet
    # running) train jobs past this shed with 429 + Retry-After derived
    # from the cost model (0 = unbounded — the pre-admission behavior)


@dataclasses.dataclass
class StoreConfig:
    backend: str = "inproc"  # "inproc" | "redis"
    host: str = "127.0.0.1"
    port: int = 6379
    timeout_s: float = 10.0  # redis socket timeout; a blackholed store
    # surfaces as OSError after at most this long — the storm harness
    # (scripts/storm_smoke.py) shrinks it so outage detection is prompt


@dataclasses.dataclass
class StoreGuardConfig:
    """Store-outage survival (service/storeguard.py): a health state
    machine (healthy/flaky/down) consulted by every durable-write path,
    a bounded per-job write-behind spool that holds fenced writes while
    the store is DOWN and replays them IN ORDER under the same fencing
    token on reconnect, and outage-aware lease semantics — a holder
    whose renewals fail while the probe proves the store unreachable
    STALLS at its next jobctl safe point instead of raising terminal
    LEASE_LOST, and resumes through the journal-gated NX reacquire when
    the store returns.

    ``enabled = false`` (the default) builds no guard objects: every
    durable write pays exactly one ``is None`` read
    (scripts/bench_smoke.sh's dispatch counters stay byte-identical).
    ``probe_every_s`` is the active-probe cadence while unhealthy (0 =
    manual ticks — tests drive ``tick()``); ``down_after`` is how many
    consecutive transport failures arm the probe for the DOWN verdict;
    ``spool_max_entries`` bounds each job's spool (overflow fences the
    job — the current terminal-failure posture, never silent loss);
    ``stall_max_s`` bounds how long a job may wait out an outage at a
    safe point before conservatively self-fencing (0 = unbounded);
    ``ephemeral_admission`` admits loudly-flagged NO-JOURNAL jobs
    during an outage instead of shedding 429 (their results ride the
    spool; a crash before the store returns loses them — the flag in
    the submit response says so).
    """

    enabled: bool = False
    probe_every_s: float = 1.0
    down_after: int = 1
    spool_max_entries: int = 512
    stall_max_s: float = 120.0
    ephemeral_admission: bool = False


@dataclasses.dataclass
class EngineConfig:
    """Boot-time engine knobs; ``None`` means the engine's own default."""

    mesh_devices: int = 0  # 0 = no mesh; N = shard seq axis over N devices
    pool_bytes: Optional[int] = None
    node_batch: Optional[int] = None
    pipeline_depth: Optional[int] = None
    chunk: Optional[int] = None  # SPADE engines (default 2048 there)
    recompute_chunk: Optional[int] = None
    tsr_chunk: Optional[int] = None  # TSR candidate batch (default: sized
    # to the eval HBM budget — see models/tsr.py TsrTPU.__init__)
    item_cap: Optional[int] = None  # TSR iterative-deepening width
    fused: Optional[str] = None  # SPADE engine routing: "auto" (default) /
    # "always" / "never" / "queue" / "dense" (engine pins) — see
    # models/spade_tpu.mine_spade_tpu
    watchdog_slack: Optional[float] = None  # dispatch watchdog: deadline =
    # max(floor, cost-model estimate x slack); None (default) disables —
    # see utils/watchdog.py (enable on TPU deployments; the estimate is
    # anchored on TPU kernel walls)
    watchdog_floor_s: Optional[float] = None  # minimum deadline (default 2.0)


@dataclasses.dataclass
class PrewarmConfig:
    """AOT prewarm envelope (service/prewarm.py): the data geometry the
    deployment expects to serve, declared so every compile is paid at
    boot instead of on the first live ``/train``/``/stream`` (the 41.7 s
    cache-miss cold start, BASELINE.json ``cold_start``).

    ``sequences``/``items``/``words``: expected dataset scale and
    frequent-projection width for batch mines (0 = skip batch shapes).
    ``maxgap``/``maxwindow``: the cSPADE constraint pair requests will
    carry (each pair compiles different kernels; unset = skip).
    ``tsr``: also compile the TSR engine's static geometry.
    ``stream_batch_sequences``/``stream_items``: the incremental
    streaming envelope (per-push micro-batch size + window frequent-item
    width; 0 = skip streaming shapes).  ``stream_seq_floor``: pin live
    batch stores to at least this sequence bucket so early small pushes
    land on the prewarmed shapes (normally = stream_batch_sequences).
    ``checkpointed``: also compile the segmented (resumable) queue
    programs.
    """

    enabled: bool = False
    sequences: int = 0
    items: int = 0
    words: int = 1
    maxgap: Optional[int] = None
    maxwindow: Optional[int] = None
    tsr: bool = False
    stream_batch_sequences: int = 0
    stream_items: int = 0
    stream_seq_floor: int = 0
    checkpointed: bool = False
    max_tokens: int = 0  # token-table bound for store-build warming
    # (0 = 8 x sequences; see utils/shapes.WorkloadSpec)


@dataclasses.dataclass
class ObservabilityConfig:
    """Flight-recorder gating (utils/obs.py).  ``trace = false`` (the
    default) pins the disabled path to one module-global read per
    probe — the same contract as the fault registry; the metrics
    registry behind ``GET /metrics`` is always on (registry writes are
    a lock + dict update, and a scrape must work on any deployment).
    ``trace_max_spans`` bounds each job's completed-span ring (oldest
    evicted first); ``trace_jobs`` bounds how many job traces are kept.

    Cluster observability plane (ISSUE 9, service/obsplane.py):
    ``spine_flush_spans`` is how many completed spans buffer per trace
    before an automatic flush to the durable spine (``fsm:trace:{uid}``;
    checkpoint saves and terminal paths flush regardless);
    ``spine_max_chunks`` bounds each uid's spine list (newest kept,
    0 = unbounded); ``slo_window_s`` is the /admin/slo sliding window.
    """

    trace: bool = False
    trace_max_spans: int = 512
    trace_jobs: int = 16
    spine_flush_spans: int = 32
    spine_max_chunks: int = 256
    slo_window_s: float = 300.0


@dataclasses.dataclass
class FusionConfig:
    """Cross-job launch fusion broker (service/fusion.py): co-schedule
    candidate waves from concurrent mines that share a device geometry
    into one super-batched launch.

    ``enabled``: route eligible engine waves through the broker (the
    disabled path costs one module-global read per dispatch probe —
    same pin as the fault registry).  ``window_ms``: the bounded fusion
    window — how long a normal/low-priority wave may wait for fusion
    peers before launching anyway (a ``high`` wave never waits: it
    launches immediately with whatever is already pending).
    ``max_jobs``: waves fused into one launch; ``max_width``: fused
    candidate-lane ceiling (the window also closes when pending lanes
    reach it).  ``dispatch_workers``: broker dispatcher threads —
    matured window groups with disjoint membership are independent
    device work, and a single serialized dispatcher would forfeit the
    concurrency the Miner worker pool feeds the broker (a group
    blocked in readback must not stall the next matured window).
    """

    enabled: bool = False
    window_ms: float = 4.0
    max_jobs: int = 8
    max_width: int = 16384
    dispatch_workers: int = 2


@dataclasses.dataclass
class PartitionConfig:
    """Equivalence-class partitioned mining (parallel/partition.py +
    models/tsr.TsrPartitioned): the candidate frontier splits by
    km-prefix class over the outer axis of a 2-D ``parts x seq`` mesh,
    each partition keeps the inner seq-axis shard + psum, and the only
    cross-partition traffic is one small exchange per round.  Output is
    byte-identical to the unpartitioned route (docs/DESIGN.md).

    ``parts = 0`` resolves at request time: one partition per process
    in a multi-controller run, else 2 when the boot mesh splits evenly,
    else partitioning stays off.  An explicit ``parts`` that cannot
    split the topology degrades to unpartitioned with a
    ``partition_config_invalid`` log line (a config typo must not fail
    every train request).  ``classes`` is the
    class-hash granularity (must comfortably exceed ``parts`` for the
    LPT balance to bite; 64 is plenty up to ~16 partitions).
    """

    enabled: bool = False
    parts: int = 0
    classes: int = 64


@dataclasses.dataclass
class MeshguardConfig:
    """Topology-survival plane (service/meshguard.py): per-partition-row
    health state machine (healthy -> suspect -> dead) fed by watchdog
    timeouts and ``device.dispatch``/``device.resident`` fault trips,
    plus an active zero-width probe per row.  Row deaths bump a
    monotonic ``topology_epoch`` published on the lease heartbeat; the
    partitioned orchestrator re-plans the dead row's equivalence
    classes LPT onto survivors and resumes from the composite frontier
    (parallel/partition.py ``replan_surviving``), byte-identical to the
    healthy mine (docs/DESIGN.md).

    ``enabled = false`` (default) keeps every dispatch probe at one
    module-global read and the pre-meshguard behavior byte-identical.
    ``dead_after`` is how many device-shaped trips move a row from
    suspect to dead (the first trip is always only suspect — one flaky
    launch must not kill a row).  ``probe_every_s`` is the active-probe
    cadence riding the lease heartbeat (0 = passive trips only).
    ``max_retries`` bounds per-round adoption attempts in the
    orchestrator before the mine fails for real (a mesh losing rows
    faster than re-planning converges is dead, not degraded).
    """

    enabled: bool = False
    dead_after: int = 2
    probe_every_s: float = 0.0
    max_retries: int = 4


@dataclasses.dataclass
class RescacheConfig:
    """Result-reuse tier above admission (service/resultcache.py):
    content-addressed dataset fingerprints, in-flight request
    coalescing (identical requests attach as followers of one
    execution with fan-out delivery), and dominance-based serving
    (a completed cached result answers strictly weaker requests by
    host-side filtering — zero device work).  The dominance predicates
    are proven conservative in docs/DESIGN.md.

    ``enabled = false`` (default) keeps the pre-rescache admission path
    byte-identical: the Miner holds no cache instance and every submit
    pays one attribute read.  ``max_bytes`` bounds the cached result
    entries with LRU eviction over a cursor SCAN (0 = unbounded).
    ``coalesce`` / ``dominance`` gate the two serving layers
    independently (fingerprinting stays on for both).
    """

    enabled: bool = False
    max_bytes: int = 67108864  # 64 MiB
    coalesce: bool = True
    dominance: bool = True


@dataclasses.dataclass
class FairnessConfig:
    """Weighted-fair multi-tenant admission (service/fairness.py):
    per-tenant token buckets layered UNDER the strict priority classes —
    within each class, queued jobs are served deficit-weighted
    round-robin across tenants, and each tenant's queue occupancy is
    capped, so one flooding tenant sheds 429s (with a Retry-After
    derived from its OWN bucket refill) while every other tenant's
    goodput holds at its weight-fair share.

    ``enabled = false`` (default) keeps the admission queue exactly as
    before — plain FIFO within each priority class, tenant param
    accepted but ignored (bench_smoke's dispatch counters stay
    byte-identical).  ``tenant_depth`` is each tenant's queued-job cap
    (its bucket size; 0 = no per-tenant cap — the global queue_depth
    still binds).  ``max_tenants`` bounds the live tenant vocabulary
    (tenant names label fsm_tenant_* series — unbounded cardinality is
    an operator hazard); a NEW tenant past the bound is refused with a
    failure envelope.  ``weights`` maps tenant name -> relative weight
    (``[fairness.weights]`` table in TOML); unlisted tenants get
    ``default_weight``.
    """

    enabled: bool = False
    tenant_depth: int = 64
    max_tenants: int = 64
    default_weight: float = 1.0
    weights: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscaleConfig:
    """Elastic control plane (service/autoscale.py): a per-replica
    controller, leader-elected through a short-TTL ``fsm:autoscale:
    leader`` lease on the shared store, watches cluster queue depth,
    free capacity and the /admin/slo p99 and emits scale decisions —
    scale-UP publishes a desired-replica-count record
    (``fsm:autoscale:desired``) an operator hook or scripts/fleet.py
    acts on; scale-DOWN writes a drain directive for the least-loaded
    replica, which stops admitting, lets peers steal its queue,
    releases its leases and exits (the PR 8 protocol).

    Requires ``[cluster] enabled`` (the lease substrate IS the control
    plane's transport).  ``up_queue_per_worker``: queued jobs per
    fleet worker above which the fleet is under-provisioned.
    ``up_p99_s``: scale up when the /admin/slo e2e p99 exceeds this
    (0 = ignore the latency signal).  ``down_free_frac``: fraction of
    fleet workers idle (with an empty queue) above which the fleet is
    over-provisioned.  ``hold_s``: a signal must persist this long
    before it becomes a decision (hysteresis — load oscillating inside
    the band produces ZERO decisions); ``cooldown_s``: minimum gap
    between decisions.  ``decide_every_s`` (0 = leader_ttl_s / 3) is
    the controller cadence; ``leader_ttl_s`` bounds how long a dead
    leader stalls the loop.  ``drain_timeout_s``: how long a draining
    replica waits for peers to steal its queue before exiting anyway
    (leftovers become journal orphans the survivors' periodic recovery
    adopts — slower, never lost).
    """

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    up_queue_per_worker: float = 2.0
    up_p99_s: float = 0.0
    # predictive scale-up (ROADMAP item 4 remainder): the leader tracks
    # the fleet's lifetime admission count (heartbeat-piggybacked),
    # EWMA-smooths its rate and the rate's derivative, and treats a
    # sustained positive derivative >= this (jobs/s per second) as an
    # up signal BEFORE the queue has built — guarded by the same hold_s
    # hysteresis as the reactive signals (0 = off, the default)
    up_rate_derivative: float = 0.0
    rate_alpha: float = 0.3
    down_free_frac: float = 0.5
    hold_s: float = 10.0
    cooldown_s: float = 30.0
    decide_every_s: float = 0.0
    leader_ttl_s: float = 3.0
    drain_timeout_s: float = 60.0


@dataclasses.dataclass
class PlannerConfig:
    """Dataset-shape-aware engine planner (service/planner.py) for
    ``algorithm=AUTO`` requests.  ``mode = "auto"`` (default) routes by
    the calibrated density crossover — patterns requests go to the SPAM
    fixed-shape wave engine when the dataset is dense enough
    (``density_crossover``) and the frequent alphabet narrow enough
    (``max_alphabet``), to the SPADE candidate-list engines otherwise;
    rules requests always route to TSR.  ``mode = "pinned"`` routes
    every AUTO to ``pinned`` unconditionally (soak/exclusion lever).
    Explicit ``algorithm=`` names bypass the planner entirely."""

    mode: str = "auto"
    pinned: str = "SPADE_TPU"
    density_crossover: float = 0.02
    max_alphabet: int = 512
    # per-item representation routing WITHIN a mine (ISSUE 16): the same
    # crossover that routes the engine routes each item to a dense SPAM
    # bitmap row or a SPADE id-list; "bitmap"/"idlist" pin a uniform
    # store (the debugging/bench fixed-representation modes)
    representation: str = "auto"
    # pattern length at which the engines switch to the dEclat diffset
    # support formulation (byte-identical by construction; 0 disables)
    diffset_depth: int = 3


@dataclasses.dataclass
class DistributedConfig:
    """Multi-host (jax.distributed) wiring; all-defaults = single host.

    ``enabled`` with empty coordinator/counts defers to JAX's own env vars
    and cloud auto-detection (see parallel/multihost.py).
    """

    enabled: bool = False
    coordinator_address: str = ""  # "" = JAX env var / auto-detect
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


@dataclasses.dataclass
class ClusterConfig:
    """Lease-fenced multi-replica service (service/lease.py): N replicas
    share one Redis journal namespace; per-job leases with fencing
    tokens make any replica's crash degrade capacity, never
    correctness.  ``enabled = false`` (default) keeps the PR 5
    single-instance posture at zero cost.

    ``replica_id`` must be unique per replica when set; "" generates one
    per boot.  ``lease_ttl_s`` bounds failover latency (a dead
    replica's jobs are adoptable after at most one TTL) and bounds how
    long a stalled replica may still believe it owns a job.
    ``heartbeat_s`` (0 = ttl/3) is the renewal cadence — /3 so two
    failed renewals still leave one attempt before the TTL lapses.
    ``steal`` lets idle replicas claim queued jobs from loaded peers.
    ``recover_every_s`` (0 = ttl) is the periodic orphan-adoption scan
    cadence.  ``max_adoptions`` is the crash-loop quarantine bound
    (service/meshguard.py + recover_orphans): a job whose journal
    intent records this many adoption resubmits settles as a durable
    ``POISON:`` failure instead of burning another replica — released
    only via ``/admin/quarantine``.
    """

    enabled: bool = False
    replica_id: str = ""
    lease_ttl_s: float = 10.0
    heartbeat_s: float = 0.0
    steal: bool = True
    recover_every_s: float = 0.0
    max_adoptions: int = 3


@dataclasses.dataclass
class PredictConfig:
    """Prediction serving plane (`POST /predict`, service/predictor.py):
    mined rule sets compile into device-resident packed tries and
    concurrent same-artifact requests fuse into one scoring wave.

    ``window_ms`` is the micro-batch window (0 disables fusion — every
    request launches solo); ``max_wave`` caps requests per wave (and
    bounds the enumerated pow2 wave ladder prewarm compiles).  ``topm``
    is the default consequent count when a request omits ``m``.
    ``lanes_floor`` / ``depth_floor`` pad every artifact UP to a shared
    geometry envelope so live predicts land on prewarmed shape keys
    (the stream_seq_floor idea applied to serving); a longer observed
    prefix or bigger rule set still works — it just compiles its own
    geometry on first touch.  ``artifact_entries`` / ``artifact_bytes``
    bound the compiled-trie LRU exactly like fusion's fused-prep cache.
    """

    enabled: bool = True
    window_ms: float = 2.0
    max_wave: int = 16
    topm: int = 8
    lanes_floor: int = 1024
    depth_floor: int = 16
    artifact_entries: int = 8
    artifact_bytes: int = 256 << 20


@dataclasses.dataclass
class IntegrityConfig:
    """Durable-state integrity plane (utils/envelope.py +
    service/integrity.py): every durable write is checksum-enveloped and
    verified on read unconditionally; this section tunes only the
    BACKGROUND SCRUBBER that verifies envelopes at rest.

    ``enabled = false`` removes the scrubber entirely (verify-on-read
    stays — it is a correctness property, not a feature).
    ``scrub_every_s`` is the pass cadence (riding the cluster heartbeat
    when one exists, a private daemon thread on solo boots; 0 = manual
    passes only, via tests/admin).  ``scrub_batch`` bounds the keys
    examined per pass — the walk carries its cursor across passes, so
    a large store is scrubbed incrementally, never in one scan storm.
    """

    enabled: bool = True
    scrub_every_s: float = 60.0
    scrub_batch: int = 256


@dataclasses.dataclass
class UsageConfig:
    """Resource attribution & usage metering plane (service/usage.py):
    per-job/per-tenant device-cost ledger with conservation guarantees.

    ``enabled = false`` (the default) removes the meter entirely —
    every dispatch-surface deposit probe then costs one module-global
    read, and dispatch behavior is byte-identical to a build without
    the plane.  ``window_s`` is the per-tenant sliding rollup window
    (the obs.SlidingQuantiles horizon behind ``/admin/usage`` window
    stats).  ``flush_every_s`` is the minimum interval between durable
    ledger flushes (riding the lease heartbeat in cluster mode, a
    private timer on solo boots).  ``top_jobs`` bounds the top-N
    settled-jobs table in ``/admin/usage``."""

    enabled: bool = False
    window_s: float = 300.0
    flush_every_s: float = 15.0
    top_jobs: int = 10


@dataclasses.dataclass
class Config:
    service: ServiceConfig = dataclasses.field(default_factory=ServiceConfig)
    store: StoreConfig = dataclasses.field(default_factory=StoreConfig)
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    distributed: DistributedConfig = dataclasses.field(
        default_factory=DistributedConfig)
    prewarm: PrewarmConfig = dataclasses.field(default_factory=PrewarmConfig)
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig)
    fusion: FusionConfig = dataclasses.field(default_factory=FusionConfig)
    partition: PartitionConfig = dataclasses.field(
        default_factory=PartitionConfig)
    cluster: ClusterConfig = dataclasses.field(
        default_factory=ClusterConfig)
    meshguard: MeshguardConfig = dataclasses.field(
        default_factory=MeshguardConfig)
    rescache: RescacheConfig = dataclasses.field(
        default_factory=RescacheConfig)
    fairness: FairnessConfig = dataclasses.field(
        default_factory=FairnessConfig)
    autoscale: AutoscaleConfig = dataclasses.field(
        default_factory=AutoscaleConfig)
    storeguard: StoreGuardConfig = dataclasses.field(
        default_factory=StoreGuardConfig)
    planner: PlannerConfig = dataclasses.field(
        default_factory=PlannerConfig)
    predict: PredictConfig = dataclasses.field(
        default_factory=PredictConfig)
    integrity: IntegrityConfig = dataclasses.field(
        default_factory=IntegrityConfig)
    usage: UsageConfig = dataclasses.field(
        default_factory=UsageConfig)
    profile_dir: str = ""  # root dir for jax.profiler traces ("" disables)
    fault_injection: bool = False  # gate for /admin/faults: arming fault
    # sites over HTTP is a chaos-lab capability, refused unless the boot
    # config opts the deployment in explicitly (utils/faults.py)


class ConfigError(ValueError):
    pass


def _fill(cls, obj: Dict[str, Any], section: str):
    if not isinstance(obj, dict):
        raise ConfigError(f"[{section}] must be a table/object, "
                          f"got {type(obj).__name__}")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(obj) - set(fields)
    if unknown:
        raise ConfigError(
            f"unknown key(s) {sorted(unknown)} in [{section}] "
            f"(valid: {sorted(fields)})")
    kwargs = {}
    for name, value in obj.items():
        f = fields[name]
        if f.type in ("int", "Optional[int]") and value is not None:
            value = int(value)
        elif f.type in ("float", "Optional[float]") and value is not None:
            value = float(value)
        elif f.type == "str":
            value = str(value)
        kwargs[name] = value
    return cls(**kwargs)


def parse_config(obj: Dict[str, Any]) -> Config:
    top = dict(obj)
    sections = {
        "service": (ServiceConfig, top.pop("service", {})),
        "store": (StoreConfig, top.pop("store", {})),
        "engine": (EngineConfig, top.pop("engine", {})),
        "distributed": (DistributedConfig, top.pop("distributed", {})),
        "prewarm": (PrewarmConfig, top.pop("prewarm", {})),
        "observability": (ObservabilityConfig,
                          top.pop("observability", {})),
        "fusion": (FusionConfig, top.pop("fusion", {})),
        "partition": (PartitionConfig, top.pop("partition", {})),
        "cluster": (ClusterConfig, top.pop("cluster", {})),
        "meshguard": (MeshguardConfig, top.pop("meshguard", {})),
        "rescache": (RescacheConfig, top.pop("rescache", {})),
        "fairness": (FairnessConfig, top.pop("fairness", {})),
        "autoscale": (AutoscaleConfig, top.pop("autoscale", {})),
        "storeguard": (StoreGuardConfig, top.pop("storeguard", {})),
        "planner": (PlannerConfig, top.pop("planner", {})),
        "predict": (PredictConfig, top.pop("predict", {})),
        "integrity": (IntegrityConfig, top.pop("integrity", {})),
        "usage": (UsageConfig, top.pop("usage", {})),
    }
    profile_dir = str(top.pop("profile_dir", ""))
    fault_injection = bool(top.pop("fault_injection", False))
    if top:
        raise ConfigError(
            f"unknown top-level key(s) {sorted(top)} "
            f"(valid: {sorted(sections) + ['fault_injection', 'profile_dir']})")
    parsed = {name: _fill(cls, section_obj, name)
              for name, (cls, section_obj) in sections.items()}
    cfg = Config(profile_dir=profile_dir, fault_injection=fault_injection,
                 **parsed)
    if cfg.store.backend not in ("inproc", "redis"):
        raise ConfigError(
            f"store.backend must be 'inproc' or 'redis', "
            f"got {cfg.store.backend!r}")
    if cfg.engine.mesh_devices < 0:
        raise ConfigError("engine.mesh_devices must be >= 0")
    if cfg.service.queue_depth < 0:
        raise ConfigError("service.queue_depth must be >= 0 (0 = unbounded)")
    if cfg.observability.trace_max_spans < 1:
        raise ConfigError("observability.trace_max_spans must be >= 1")
    if cfg.observability.trace_jobs < 1:
        raise ConfigError("observability.trace_jobs must be >= 1")
    if cfg.observability.spine_flush_spans < 1:
        raise ConfigError("observability.spine_flush_spans must be >= 1")
    if cfg.observability.spine_max_chunks < 0:
        raise ConfigError(
            "observability.spine_max_chunks must be >= 0 (0 = unbounded)")
    if cfg.observability.slo_window_s <= 0:
        raise ConfigError("observability.slo_window_s must be > 0")
    if cfg.engine.fused not in (None, "auto", "always", "never",
                                "queue", "dense"):
        raise ConfigError(
            f"engine.fused must be 'auto', 'always', 'never', 'queue' "
            f"or 'dense', got {cfg.engine.fused!r}")
    if cfg.fusion.window_ms < 0:
        raise ConfigError("fusion.window_ms must be >= 0")
    if cfg.fusion.max_jobs < 1:
        raise ConfigError("fusion.max_jobs must be >= 1")
    if cfg.fusion.max_width < 32:
        raise ConfigError("fusion.max_width must be >= 32 (one jnp lane)")
    if cfg.fusion.dispatch_workers < 1:
        raise ConfigError("fusion.dispatch_workers must be >= 1")
    if cfg.partition.parts < 0:
        raise ConfigError("partition.parts must be >= 0 (0 = auto)")
    if cfg.partition.classes < 1:
        raise ConfigError("partition.classes must be >= 1")
    if (cfg.partition.parts > 1
            and cfg.partition.classes < cfg.partition.parts):
        raise ConfigError(
            "partition.classes must be >= partition.parts (each "
            "partition needs at least one equivalence class to own)")
    if cfg.cluster.lease_ttl_s <= 0:
        raise ConfigError("cluster.lease_ttl_s must be > 0")
    if cfg.cluster.heartbeat_s < 0:
        raise ConfigError("cluster.heartbeat_s must be >= 0 (0 = ttl/3)")
    if (cfg.cluster.heartbeat_s
            and cfg.cluster.heartbeat_s >= cfg.cluster.lease_ttl_s):
        raise ConfigError(
            "cluster.heartbeat_s must be < cluster.lease_ttl_s (a lease "
            "renewed slower than it expires is permanently flapping)")
    if cfg.cluster.recover_every_s < 0:
        raise ConfigError("cluster.recover_every_s must be >= 0 (0 = ttl)")
    if cfg.cluster.max_adoptions < 1:
        raise ConfigError(
            "cluster.max_adoptions must be >= 1 (every orphan deserves "
            "at least one adoption before quarantine)")
    if cfg.meshguard.dead_after < 1:
        raise ConfigError("meshguard.dead_after must be >= 1")
    if cfg.meshguard.probe_every_s < 0:
        raise ConfigError(
            "meshguard.probe_every_s must be >= 0 (0 = passive only)")
    if cfg.meshguard.max_retries < 1:
        raise ConfigError("meshguard.max_retries must be >= 1")
    if cfg.rescache.max_bytes < 0:
        raise ConfigError("rescache.max_bytes must be >= 0 (0 = unbounded)")
    if cfg.fairness.tenant_depth < 0:
        raise ConfigError(
            "fairness.tenant_depth must be >= 0 (0 = no per-tenant cap)")
    if cfg.fairness.max_tenants < 1:
        raise ConfigError("fairness.max_tenants must be >= 1")
    if cfg.fairness.default_weight <= 0:
        raise ConfigError("fairness.default_weight must be > 0")
    if not isinstance(cfg.fairness.weights, dict):
        raise ConfigError("[fairness.weights] must be a table of "
                          "tenant -> weight")
    weights = {}
    for name, w in cfg.fairness.weights.items():
        try:
            w = float(w)
        except (TypeError, ValueError):
            raise ConfigError(
                f"fairness weight for tenant {name!r} must be a number, "
                f"got {w!r}")
        if w <= 0:
            raise ConfigError(
                f"fairness weight for tenant {name!r} must be > 0")
        weights[str(name)] = w
    cfg.fairness.weights = weights
    if cfg.autoscale.enabled and not cfg.cluster.enabled:
        raise ConfigError(
            "autoscale.enabled requires cluster.enabled (the autoscaler "
            "leader-elects and observes the fleet through the lease "
            "substrate)")
    if cfg.autoscale.min_replicas < 1:
        raise ConfigError("autoscale.min_replicas must be >= 1")
    if cfg.autoscale.max_replicas < cfg.autoscale.min_replicas:
        raise ConfigError(
            "autoscale.max_replicas must be >= autoscale.min_replicas")
    if cfg.autoscale.up_queue_per_worker <= 0:
        raise ConfigError("autoscale.up_queue_per_worker must be > 0")
    if cfg.autoscale.up_p99_s < 0:
        raise ConfigError("autoscale.up_p99_s must be >= 0 (0 = ignore)")
    if not 0 < cfg.autoscale.down_free_frac <= 1:
        raise ConfigError("autoscale.down_free_frac must be in (0, 1]")
    if cfg.autoscale.up_rate_derivative < 0:
        raise ConfigError(
            "autoscale.up_rate_derivative must be >= 0 (0 = off)")
    if not 0 < cfg.autoscale.rate_alpha <= 1:
        raise ConfigError("autoscale.rate_alpha must be in (0, 1]")
    if cfg.autoscale.hold_s < 0 or cfg.autoscale.cooldown_s < 0:
        raise ConfigError(
            "autoscale.hold_s / cooldown_s must be >= 0")
    if cfg.autoscale.decide_every_s < 0:
        raise ConfigError(
            "autoscale.decide_every_s must be >= 0 (0 = leader_ttl_s / 3)")
    if cfg.autoscale.leader_ttl_s <= 0:
        raise ConfigError("autoscale.leader_ttl_s must be > 0")
    if cfg.autoscale.drain_timeout_s <= 0:
        raise ConfigError("autoscale.drain_timeout_s must be > 0")
    if cfg.store.timeout_s <= 0:
        raise ConfigError("store.timeout_s must be > 0")
    if cfg.storeguard.probe_every_s < 0:
        raise ConfigError(
            "storeguard.probe_every_s must be >= 0 (0 = manual ticks)")
    if cfg.storeguard.down_after < 1:
        raise ConfigError("storeguard.down_after must be >= 1")
    if cfg.storeguard.spool_max_entries < 1:
        raise ConfigError("storeguard.spool_max_entries must be >= 1")
    if cfg.storeguard.stall_max_s < 0:
        raise ConfigError(
            "storeguard.stall_max_s must be >= 0 (0 = unbounded)")
    if cfg.planner.mode not in ("auto", "pinned"):
        raise ConfigError(
            f"planner.mode must be 'auto' or 'pinned', "
            f"got {cfg.planner.mode!r}")
    # ONE vocabulary: the planner's concrete-engine tuple (lazy import —
    # planner imports this module at top level, so the edge must stay
    # function-local here); a future engine added there is pinnable
    # with no second list to update
    from spark_fsm_tpu.service.planner import CONCRETE_ENGINES

    if cfg.planner.pinned not in CONCRETE_ENGINES:
        raise ConfigError(
            f"planner.pinned must be a concrete engine "
            f"{list(CONCRETE_ENGINES)}, got {cfg.planner.pinned!r}")
    if not 0 <= cfg.planner.density_crossover <= 1:
        raise ConfigError("planner.density_crossover must be in [0, 1]")
    if cfg.planner.max_alphabet < 1:
        raise ConfigError("planner.max_alphabet must be >= 1")
    if cfg.planner.representation not in ("auto", "bitmap", "idlist"):
        raise ConfigError(
            f"planner.representation must be 'auto', 'bitmap' or "
            f"'idlist', got {cfg.planner.representation!r}")
    if cfg.planner.diffset_depth < 0:
        raise ConfigError(
            "planner.diffset_depth must be >= 0 (0 disables diffsets)")
    if cfg.predict.window_ms < 0:
        raise ConfigError("predict.window_ms must be >= 0 (0 = no fusion)")
    if cfg.predict.max_wave < 1:
        raise ConfigError("predict.max_wave must be >= 1")
    if cfg.predict.topm < 1:
        raise ConfigError("predict.topm must be >= 1")
    if cfg.predict.lanes_floor < 0 or cfg.predict.depth_floor < 0:
        raise ConfigError(
            "predict.lanes_floor / depth_floor must be >= 0 "
            "(0 = size each artifact exactly; no shared prewarm envelope)")
    if cfg.predict.artifact_entries < 1:
        raise ConfigError("predict.artifact_entries must be >= 1")
    if cfg.predict.artifact_bytes < 1:
        raise ConfigError("predict.artifact_bytes must be >= 1")
    if cfg.integrity.scrub_every_s < 0:
        raise ConfigError(
            "integrity.scrub_every_s must be >= 0 (0 = manual passes)")
    if cfg.integrity.scrub_batch < 1:
        raise ConfigError("integrity.scrub_batch must be >= 1")
    if cfg.usage.window_s <= 0:
        raise ConfigError("usage.window_s must be > 0")
    if cfg.usage.flush_every_s < 0:
        raise ConfigError(
            "usage.flush_every_s must be >= 0 (0 = flush every tick)")
    if cfg.usage.top_jobs < 1:
        raise ConfigError("usage.top_jobs must be >= 1")
    return cfg


def load_config(path: str) -> Config:
    """Load a TOML (``.toml``) or JSON boot config file."""
    with open(path, "rb") as fh:
        raw = fh.read()
    if path.endswith(".toml"):
        try:
            import tomllib  # py >= 3.11
        except ImportError:  # py 3.10: the API-identical backport
            import tomli as tomllib

        obj = tomllib.loads(raw.decode("utf-8"))
    else:
        obj = json.loads(raw.decode("utf-8"))
    if not isinstance(obj, dict):
        raise ConfigError("config root must be a table/object")
    return parse_config(obj)


# --------------------------------------------------------------------------
# Process-wide active config (set once at boot by app.main; tests may swap)
# --------------------------------------------------------------------------

_lock = threading.Lock()
_active = Config()
_mesh_cache: Dict[int, Any] = {}


def get_config() -> Config:
    return _active


def set_config(cfg: Config) -> None:
    global _active
    with _lock:
        _active = cfg
        _mesh_cache.clear()
    # the watchdog policy is process-global (engines read it at dispatch
    # time, no constructor plumbing) — the active config owns it
    from spark_fsm_tpu.utils import watchdog

    watchdog.configure(
        slack=cfg.engine.watchdog_slack,
        floor_s=(2.0 if cfg.engine.watchdog_floor_s is None
                 else cfg.engine.watchdog_floor_s))
    # the flight recorder is process-global too (engines open spans
    # with no constructor plumbing) — same ownership as the watchdog
    from spark_fsm_tpu.utils import obs

    obs.configure_tracing(cfg.observability.trace,
                          max_spans=cfg.observability.trace_max_spans,
                          max_jobs=cfg.observability.trace_jobs)
    # the fusion broker is process-global like the two above (engines
    # probe it at dispatch time with no constructor plumbing)
    from spark_fsm_tpu.service import fusion

    fusion.configure(cfg.fusion)
    # cluster observability plane knobs (spine flush/retention, SLO
    # window) — same process-global ownership as the three above
    from spark_fsm_tpu.service import obsplane

    obsplane.configure(cfg.observability)
    # the prediction plane's broker window + artifact cache budgets are
    # process-global like fusion's (the Master routes into module state)
    from spark_fsm_tpu.service import predictor

    predictor.configure(cfg.predict)
    # the integrity plane's scrubber cadence/batch are process-global
    # like the planes above (read sites count into module counters; the
    # Miner installs the scrubber over its store)
    from spark_fsm_tpu.service import integrity

    integrity.configure(cfg.integrity)
    # the usage metering plane's meter knobs are process-global like
    # the integrity scrubber's (dispatch surfaces deposit into module
    # state; the Miner installs the meter over its store)
    from spark_fsm_tpu.service import usage

    usage.configure(cfg.usage)


def engine_kwargs(*names: str) -> Dict[str, Any]:
    """Configured engine knobs (subset ``names``, skipping unset ones)."""
    eng = _active.engine
    out = {}
    for name in names:
        value = getattr(eng, name)
        if value is not None:
            out[name] = value
    return out


def get_mesh():
    """The boot-configured device mesh, or None for single-chip."""
    n = _active.engine.mesh_devices
    if n <= 0:
        return None
    with _lock:
        if n not in _mesh_cache:
            from spark_fsm_tpu.parallel.mesh import make_mesh

            _mesh_cache[n] = make_mesh(n)
        return _mesh_cache[n]
